"""Per-node service: scheduler, worker pool, object directory, actor manager.

Equivalent role to the reference's raylet (``src/ray/raylet/node_manager.h:125``
— worker leasing, dependency management, dispatch) fused with the
owner-side core-worker duties (``core_worker/task_manager.h:173`` — retries,
``object_recovery_manager.h`` — failure handling). One service per node; a
single dispatcher thread owns all mutable state (the reference gets the same
discipline from its asio event loop); per-connection reader threads feed a
queue. Workers are real OS processes talking framed messages over a unix
socket; object payloads ride shared memory (``object_store.py``).
"""

from __future__ import annotations

import itertools
import json
import os
import queue
import random
import signal
import socket
import subprocess
import sys
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from .. import exceptions
from . import events
from . import fieldsan
from . import history as history_mod
from . import locksan
from . import memory_monitor
from . import protocol as P
from . import scheduler as sched
from . import telemetry
from .config import CONFIG
from .gcs import (ACTOR_ALIVE, ACTOR_DEAD, ACTOR_PENDING, ACTOR_RESTARTING,
                  GlobalControlPlane, NodeInfo, PG_LOST, TaskEvent)
from .ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from . import object_store
from .object_store import ObjectMeta, ObjectStore
from .rpc import RpcChannel
from .serialization import to_bytes

_WORKER_STATES = ("STARTING", "IDLE", "BUSY", "ACTOR", "DEAD")


@dataclass
class _Worker:
    worker_id: WorkerID
    proc: Optional[subprocess.Popen] = None
    conn: Optional[P.Connection] = None
    conn_key: Optional[int] = None
    state: str = "STARTING"
    task: Optional["_TaskRecord"] = None
    # same-shape tasks leased to this worker beyond the running one
    # (reference: worker-lease reuse — the owner pushes tasks to a
    # leased worker without a per-task raylet round trip,
    # ``lease_policy.h`` / ``direct_task_transport.h``). Only the
    # running task holds the resource charge; the charge transfers on
    # each completion since every piped task has the identical shape.
    pipeline: "deque" = field(default_factory=deque)
    # monotonic per-worker lease grant counter: every EXECUTE pushed to
    # this worker (assignment or pipelined lease) carries the next seq,
    # and the worker echoes it on RETURN_LEASED — a rescue that names a
    # superseded grant is provably stale and is dropped instead of
    # un-assigning whatever the task's CURRENT grant is (the sequenced
    # handshake that made pipelining default-on; reference analogue:
    # lease ids in ``direct_task_transport.h``)
    lease_seq: int = 0
    actor_id: Optional[ActorID] = None
    started_at: float = field(default_factory=time.monotonic)
    # when the current task/actor work was assigned — pooled workers are
    # reused, so the OOM RetriableLIFO must rank by work recency, not
    # process age
    assigned_at: float = 0.0
    # runtime-env pool key (reference: WorkerPool keyed by runtime env,
    # ``worker_pool.h:152``); "" = the default environment
    env_key: str = ""
    idle_since: float = 0.0
    log_path: Optional[str] = None
    # human name for log attribution (SET_LOG_LABEL — e.g. a serve
    # replica's "deployment#tag"); rides every published LOG batch so
    # driver-side prefixes are greppable by deployment
    log_label: Optional[str] = None
    # set just before the memory monitor kills the process, so the
    # conn-closed path reports OutOfMemoryError rather than a crash
    oom_victim: bool = False
    # OS pid from the REGISTER handshake, for workers this node did not
    # spawn itself (proc is None for those)
    pid: Optional[int] = None
    # threads of this process currently parked in a blocking get(),
    # whether or not the running record holds a CPU charge (an ACTOR
    # method's record doesn't — the creation does). Workers counted
    # here are exempt from the pool cap: an actor blocked on a nested
    # actor creation (e.g. a collective-group coordinator) would
    # otherwise deadlock a full pool that only it can unblock
    blocked_gets: int = 0
    # registration deadline override (pip-env workers build a venv before
    # they can register; 0 = plain CONFIG.worker_register_timeout_s)
    register_timeout_s: float = 0.0
    # True while the spawn includes a runtime-env build: a
    # killed-at-deadline then counts as an ENV failure (the build hung),
    # not as load
    env_setup: bool = False


@dataclass
class _TaskRecord:
    spec: P.TaskSpec
    kind: str = "task"                    # task | actor_create | actor_call
    deps: Dict[ObjectID, ObjectMeta] = field(default_factory=dict)
    remaining_deps: Set[ObjectID] = field(default_factory=set)
    retries_left: int = 0
    # OOM kills are budgeted separately from task failures (reference:
    # task_oom_retries) — transient memory pressure shouldn't consume
    # the user's max_retries
    oom_retries_left: int = 0
    worker_id: Optional[WorkerID] = None
    charge: Optional[Dict[str, float]] = None
    pg_key: Optional[tuple] = None
    actor_spec: Optional[P.ActorSpec] = None
    cancelled: bool = False
    # stores actually pinned at dispatch, so unpin hits the same store
    # even if the object's directory entry changes mid-task
    pinned_stores: Dict[ObjectID, Any] = field(default_factory=dict)
    # count of worker threads currently blocked in a get(); the CPU
    # charge is returned to the pool while > 0 (a bool would mispair
    # when a task's user threads block concurrently — the first
    # unblock would re-charge while others still wait)
    blocked_depth: int = 0
    # when this record entered the local pending queue — a task starved
    # here past the spillback delay gets re-routed if capacity opened
    # elsewhere
    queued_at: float = field(default_factory=time.monotonic)
    # exclusive TPU slot indices held while running (whole-chip demands)
    accel_ids: Optional[List[int]] = None
    # True once a worker handed this lease back (it sat behind a
    # blocking task): never pipe it again — one bounce max per task,
    # so rescue storms terminate and normal scheduling takes over
    no_pipe: bool = False
    # seq of the grant currently dispatching this task (see
    # _Worker.lease_seq); a RETURN_LEASED naming any other seq is stale
    lease_seq: int = 0



_PIPE_DEBUG = os.environ.get("RTPU_PIPE_DEBUG") == "1"


def _pdbg(msg):
    if _PIPE_DEBUG:
        print(f"[pipe {os.getpid()} {time.monotonic():.3f}] {msg}",
              file=sys.stderr, flush=True)

@fieldsan.guarded
class _PendingQueue:
    """Ready-to-dispatch tasks bucketed by scheduling shape
    (pg, resources, env).

    Dispatch cost per event is O(#distinct shapes + #assigned) instead
    of O(#pending): a shape that fails to fit blocks only its own
    bucket, and a 10k-task burst of one shape is a single head probe —
    the flat-deque scan made every completion O(pending) and bursts
    O(pending²) (reference analogue: schedulable-queue buckets per
    resource shape, ``cluster_task_manager.cc``)."""

    def __init__(self, env_key_fn):
        self._by_shape: Dict[tuple, deque] = {}
        self._env_key_fn = env_key_fn
        self._n = 0
        self._seq = 0

    def append(self, rec: "_TaskRecord") -> None:
        shape = (rec.pg_key,
                 tuple(sorted(rec.spec.resources.items())),
                 self._env_key_fn(rec))
        rec._pending_shape = shape
        self._seq += 1
        rec._pending_seq = self._seq
        q = self._by_shape.get(shape)
        if q is None:
            q = self._by_shape[shape] = deque()
        q.append(rec)
        self._n += 1

    def __len__(self) -> int:
        return self._n

    def __iter__(self):
        for q in list(self._by_shape.values()):
            yield from q

    def shapes(self) -> list:
        """Shapes ordered by their OLDEST member, so freed capacity goes
        to the longest-waiting task first (global-FIFO-like fairness —
        a continuously fed bucket must not starve the others)."""
        return sorted(
            (s for s, q in self._by_shape.items() if q),
            key=lambda s: self._by_shape[s][0]._pending_seq)

    def bucket(self, shape) -> deque:
        return self._by_shape.get(shape) or deque()

    def popleft(self, shape) -> "_TaskRecord":
        rec = self._by_shape[shape].popleft()
        self._n -= 1
        return rec

    def remove(self, rec: "_TaskRecord") -> bool:
        """Purge a (cancelled) record wherever it sits in its bucket."""
        shape = getattr(rec, "_pending_shape", None)
        q = self._by_shape.get(shape)
        if q is None:
            return False
        try:
            q.remove(rec)
        except ValueError:
            return False
        self._n -= 1
        if not q:
            del self._by_shape[shape]
        return True

    def drop_empty(self, shape) -> None:
        q = self._by_shape.get(shape)
        if q is not None and not q:
            del self._by_shape[shape]


@dataclass
class _OwnedTask:
    """Owner-side record of a submitted task, for retry on node failure.

    Reference analogue: ``TaskManager`` lineage entries
    (``core_worker/task_manager.h:369`` RetryTaskIfPossible).
    """

    spec: P.TaskSpec
    kind: str
    retries_left: int
    assigned_node: Optional[NodeID] = None
    actor_spec: Optional[P.ActorSpec] = None
    done: bool = False


@dataclass
class _Waiter:
    req_id: int
    conn_key: int
    object_ids: List[ObjectID]
    remaining: Set[ObjectID] = field(default_factory=set)
    num_returns: int = 0                  # for WAIT; 0 means GET (need all)
    timer: Optional[threading.Timer] = None
    fired: bool = False
    # cross-host driver GET: inline payload bytes into the reply metas
    fetch: bool = False
    # registration time + next-probe stamp/backoff for the tick's
    # stalled-waiter rescue (fruitless probes back off exponentially so
    # waiters on genuinely still-running producers don't cost a plane
    # lookup per oid per tick)
    born: float = field(default_factory=time.monotonic)
    probe_at: float = field(default_factory=lambda: time.monotonic() + 1.0)
    probe_backoff: float = 1.0


class _RemotePeer:
    """Handle to a node service in another OS process (network plane).

    Carries the cross-node surface ``NodeService`` uses on its peers:
    task/actor forwarding (``post_remote``), the object plane
    (``get_meta``/``pin_and_get``/``unpin``) and PG bundle reservation.
    Same-host peers exchange objects by shm name (zero-copy through
    /dev/shm); cross-host peers pull payload bytes and adopt a local
    secondary copy (reference: ``object_manager.h:117`` Push/Pull).
    Requests are answered on the peer's connection-reader thread, never
    its dispatcher, so two nodes calling into each other cannot
    deadlock."""

    def __init__(self, node: "NodeService", info):
        self.node = node
        self.node_id = info.node_id
        self.same_host = bool(info.host) and info.host == node.host
        self._chan = RpcChannel(P.connect_address(info.address, timeout=10.0))
        self._timeout = CONFIG.worker_lease_timeout_s
        self.dead = False

    @property
    def closed(self) -> bool:
        return self._chan.closed

    def close(self) -> None:
        self._chan.close()

    def post_remote(self, item: tuple) -> None:
        try:
            self._chan.send(P.NODE_POST, item)
        except OSError:
            pass

    # ----- object plane (duck-types the ObjectStore read surface)
    def get_meta(self, oid: ObjectID) -> Optional[ObjectMeta]:
        try:
            if self.same_host:
                return self._chan.request(
                    P.OBJ_GET_META, lambda r: (r, oid, False),
                    timeout=self._timeout)
            return self._pull(oid, pin=False)
        except Exception:
            return None

    def pin_and_get(self, oid: ObjectID) -> Optional[ObjectMeta]:
        try:
            if self.same_host:
                return self._chan.request(
                    P.OBJ_GET_META, lambda r: (r, oid, True),
                    timeout=self._timeout)
            return self._pull(oid, pin=True)
        except Exception:
            return None

    def unpin(self, oid: ObjectID) -> None:
        if self.same_host:
            try:
                self._chan.send(P.OBJ_UNPIN, oid)
            except OSError:
                pass
        else:
            self.node.store.unpin(oid)

    def _pull(self, oid: ObjectID, pin: bool) -> Optional[ObjectMeta]:
        store = self.node.store
        if store.contains(oid):
            return store.pin_and_get(oid) if pin else store.get_meta(oid)
        # chunked pull (reference: object_manager.h:117): the first chunk
        # also carries the owner's meta, so small objects cost one RTT
        # and large ones stream in bounded frames instead of one
        # payload-sized message
        chunk = CONFIG.object_transfer_chunk_bytes
        res = self._chan.request(
            P.OBJ_PULL_CHUNK, lambda r: (r, oid, 0, chunk),
            timeout=self._timeout)
        if res is None:
            return None
        meta, data = res
        if data is None:
            return meta          # inline / error values travel in the meta
        if meta.size <= len(data):
            store.adopt_payload(oid, data)
        else:
            writer = store.adopt_begin(oid, meta.size)
            try:
                writer.write(0, data)
                # windowed stream (reference: object_manager keeps
                # several chunks in flight): overlap RTTs instead of
                # paying one per chunk serially
                offsets = deque(range(len(data), meta.size, chunk))
                window: deque = deque()
                def issue():
                    off = offsets.popleft()
                    window.append((off, self._chan.request_async(
                        P.OBJ_PULL_CHUNK,
                        lambda r, off=off: (r, oid, off, chunk))))
                for _ in range(min(4, len(offsets))):
                    issue()
                while window:
                    off, fut = window.popleft()
                    res = fut.result(timeout=self._timeout)
                    if res is None or res[1] is None or not res[1]:
                        writer.abort()   # owner lost/evicted it mid-stream
                        return None
                    writer.write(off, res[1])
                    if offsets:
                        issue()
            except BaseException:
                writer.abort()
                raise
            writer.finish()
        return store.pin_and_get(oid) if pin else store.get_meta(oid)

    # ----- placement groups
    def reserve_bundle(self, pg_key: tuple, demand: Dict[str, float]) -> bool:
        try:
            return bool(self._chan.request(
                P.PG_RESERVE, lambda r: (r, pg_key, demand),
                timeout=self._timeout))
        except Exception:
            return False

    def release_bundle(self, pg_key: tuple) -> None:
        try:
            self._chan.send(P.PG_RELEASE, pg_key)
        except OSError:
            pass

    def peek(self, oid: ObjectID) -> Optional[ObjectMeta]:
        """Metadata-only existence probe: never transfers the payload
        (a cross-host wait() on a huge object must not download it)."""
        try:
            return self._chan.request(P.OBJ_GET_META,
                                      lambda r: (r, oid, False),
                                      timeout=self._timeout)
        except Exception:
            return None

    def node_stats(self, what, timeout: Optional[float] = None) -> Any:
        # debug collections ("stacks"/"profile" tuples) pass their own
        # timeout: a profile's duration can exceed the lease timeout
        try:
            return self._chan.request(P.NODE_STATS, lambda r: (r, what),
                                      timeout=timeout or self._timeout)
        except Exception:
            return None

    def coll_forward(self, body: tuple) -> None:
        """Forward one collective chunk to this peer's node, which
        delivers it to the destination process (fire and forget — a
        lost chunk surfaces as the receiving rank's deadline)."""
        try:
            self._chan.send(P.COLL_FWD, body)
        except OSError:
            pass


@fieldsan.guarded
class NodeService:
    """One per node. ``head=True`` also hosts the control plane."""

    def __init__(self, gcs: GlobalControlPlane, session_dir: str,
                 resources: Dict[str, float], node_id: Optional[NodeID] = None,
                 labels: Optional[Dict[str, str]] = None):
        self.gcs = gcs
        self.node_id = node_id or NodeID.from_random()
        self.session_dir = session_dir
        os.makedirs(session_dir, exist_ok=True)
        self.socket_path = os.path.join(
            session_dir, f"node_{self.node_id.hex()[:12]}.sock")
        self.store = ObjectStore(
            spill_dir=os.path.join(session_dir, "spill", self.node_id.hex()[:12]))

        self._res_lock = locksan.lock("node.res")
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)
        self.pg_reservations: Dict[tuple, Dict[str, float]] = {}
        self.pg_bundle_total: Dict[tuple, Dict[str, float]] = {}

        self._events: "queue.SimpleQueue" = queue.SimpleQueue()
        self._conns: Dict[int, P.Connection] = {}
        self._conn_kind: Dict[int, int] = {}
        self._conn_worker: Dict[int, WorkerID] = {}
        # collective data plane routing: worker-id binary -> conn, for
        # every registered process (workers AND drivers — a driver can
        # be a collective rank). Written on the dispatcher (REGISTER /
        # conn_closed), read on reader threads; dict ops are atomic.
        self._coll_conns: Dict[bytes, P.Connection] = {}
        self._conn_coll_wid: Dict[int, bytes] = {}
        # node-id binary -> resolved peer handle for chunk forwarding:
        # _peer() starts with a gcs.get_node (an RPC on non-head nodes)
        # and the chunk plane must not pay a control-plane round trip
        # per chunk; entries are revalidated by their own closed/dead
        # flags, so a restarted peer re-resolves on first failure
        self._coll_peers: Dict[bytes, Any] = {}
        # conn keys are minted on BOTH accept threads (unix + tcp):
        # itertools.count.__next__ is GIL-atomic, where the former
        # `key = n; n += 1` could mint the same key on both threads
        # and alias two connections in _conns (found by the ISSUE-15
        # guarded-by audit)
        self._conn_keys = itertools.count(1)
        self._workers: Dict[WorkerID, _Worker] = {}
        self._idle: deque = deque()
        self._num_starting = 0
        self._max_workers = max(int(resources.get("CPU", 4)) * 2, 8)
        # consecutive startup failures per env_key; after
        # CONFIG.worker_startup_max_failures, pending tasks needing that
        # env fail fast instead of respawning forever (reference:
        # PopWorker failure callback, ``worker_pool.h:152``)
        self._env_spawn_failures: Dict[str, int] = {}
        self._env_spawn_error: Dict[str, str] = {}

        # versioned resource sync state (RaySyncer-equivalent): a
        # time-epoch base keeps versions monotonic across a node-process
        # restart under the same id
        self._resource_version = int(time.time() * 1000)
        self._last_hb_at = 0.0
        self._hb_count = 0
        self._last_hb_snapshot: Optional[Dict[str, float]] = None
        self._last_hb_pending: Optional[list] = None
        self._pending = _PendingQueue(self._rec_env_key)  # ready-to-dispatch
        # per-worker EXECUTE outbox: sends coalesce across one event
        # (a SUBMIT_BATCH of 100 tiny tasks becomes one frame per
        # worker, not 100); flushed at the end of every dispatcher
        # event by _dispatch_loop
        self._exec_outbox: Dict[WorkerID, List[tuple]] = {}
        # per-connection reply outbox (dispatcher-thread replies only):
        # GET/WAIT replies coalesce across one event batch into one
        # frame per client — see _reply_batched
        self._reply_outbox: Dict[int, List[tuple]] = {}
        # True while draining a SUBMIT_BATCH: _queue_local defers its
        # per-spec _dispatch so the burst is one scheduling pass
        self._in_batch = False
        # resources routed to a peer but not yet visible in its gossiped
        # availability: {node_id: [(monotonic_ts, resources,
        # resource_version_at_debit), ...]}. Subtracted from _candidates
        # so a burst doesn't pile onto one node through a stale view
        # (RaySyncer-staleness bridge); a debit expires when the peer
        # gossips a NEWER snapshot (version advance) or at the TTL.
        self._route_debits: Dict[NodeID, List[tuple]] = {}
        # last gossiped resource_version per node (stamped by
        # _candidates, consumed by _debit_route)
        self._node_versions: Dict[NodeID, int] = {}
        # where each task WE submitted ran, outliving the _owned entry
        # (popped at completion): the read path probes this node's
        # store before asking the head's directory (owner-based
        # location resolution, reference:
        # ownership_based_object_directory.h). Bounded FIFO.
        self._task_origin: "OrderedDict[TaskID, NodeID]" = OrderedDict()
        self._waiting_deps: Dict[TaskID, _TaskRecord] = {}
        self._dep_index: Dict[ObjectID, Set[TaskID]] = {}
        self._running: Dict[TaskID, _TaskRecord] = {}
        self._owned: Dict[TaskID, _OwnedTask] = {}

        self._actors: Dict[ActorID, dict] = {}            # local actor state
        self._actor_queues: Dict[ActorID, deque] = {}
        # owners with a dep-waiting call in flight per actor: later calls
        # from the same owner must NOT overtake it — actor tasks execute
        # in per-submitter order (reference: actor_scheduling_queue.cc
        # sequence numbers); other owners' calls may interleave freely
        self._actor_blocked_owners: Dict[ActorID, set] = {}

        self._get_waiters: Dict[int, _Waiter] = {}
        self._wait_waiters: Dict[int, _Waiter] = {}
        # parked GEN_NEXT requests: {(task_id, index): [(conn_key,
        # req_id), ...]} — resolved when the item seals or the stream
        # ends short of the index
        self._gen_waiters: Dict[tuple, List[Tuple[int, int]]] = {}
        # last-known consumer credit per stream (from GEN events): a
        # consumed/close that lands BEFORE the producer task starts here
        # must still reach the worker — relayed on its first GEN_ITEM
        self._gen_consumed_cache: Dict[Any, int] = {}
        # node-local stream records for streaming tasks that ran here:
        # produced/done counters answered without the head (reference:
        # generator state is owner-hosted, core_worker.proto:396)
        self._gen_local: Dict[Any, dict] = {}
        self._obj_waiter_index: Dict[ObjectID, Set[int]] = {}
        self._next_waiter = 1

        self._stopped = threading.Event()
        self._threads: List[threading.Thread] = []
        self._listener: Optional[socket.socket] = None
        self._tcp_listener: Optional[socket.socket] = None
        self.tcp_address: Optional[str] = None
        self._driver_conn_keys: Set[int] = set()
        self.dead = False

        # OS-host identity for the object plane (same host = shared
        # /dev/shm); overridable to simulate cross-host transfer in tests
        self.host = os.environ.get("RTPU_NODE_HOST") or socket.gethostname()
        self._peers: Dict[NodeID, _RemotePeer] = {}

        # reference counting: objects each client connection holds (edge
        # transitions forwarded to the control plane), and in-flight
        # lineage reconstructions (reference: reference_count.h:61 +
        # object_recovery_manager.h:90)
        self._conn_refs: Dict[int, Set[ObjectID]] = {}
        self._reconstructing: Set[ObjectID] = set()

        # tasks/actors with no feasible node, parked while the
        # autoscaler adds capacity (reference: infeasible task queue,
        # ``cluster_task_manager.cc``); (deadline, kind, spec)
        self._infeasible: List[tuple] = []
        # set while re-routing a parked item so a repeat park keeps the
        # ORIGINAL deadline (the grace window must not reset under churn)
        self._repark_deadline: Optional[float] = None

        self._memory_monitor = memory_monitor.MemoryMonitor()
        self._last_mem_check = 0.0

        # per-instance TPU slots (reference: resource-instance ids):
        # whole-chip demands get exclusive indices; fractional shares
        # are capacity-only
        self._tpu_free: deque = deque(
            range(int(self.resources_total.get("TPU", 0))))

        # set in start() when a TCP plane exists (see the probe comment)
        self.shm_probe_path: Optional[str] = None
        self.shm_probe_token: Optional[str] = None

        # actor calls parked while their actor is between nodes
        # (node-death reroute window; see _submit_actor_task)
        self._reroute_parked: Dict[ActorID, List[P.TaskSpec]] = {}

        # structured lifecycle events (reference: src/ray/util/event.h)
        self.events = events.EventLogger(session_dir, self.node_id.hex(),
                                         gcs=gcs)

        # in-flight debug collections (stack dumps / profiles): token ->
        # Future resolved by STACK_REPLY/PROFILE_REPORT on the replying
        # connection's reader thread — never the dispatcher, so a stack
        # request cannot deadlock against task handling
        self._debug_lock = locksan.lock("node.debug")
        self._debug_futures: Dict[int, Future] = {}
        self._next_debug_token = 1
        # short-TTL cache of the last collective-health report: one dead
        # rank makes every survivor diagnose near-simultaneously, and W
        # identical cluster-wide fan-outs at the exact moment the
        # cluster is wedged would be a thundering herd
        self._coll_health_cache: Tuple[float, Optional[dict]] = (0.0,
                                                                 None)

        self._rng = random.Random(self.node_id.binary())

        # pre-built telemetry tag tuple: the record path is hot (every
        # submit/dispatch/seal), so the tags must not be rebuilt per call
        self._mtags = (("node", self.node_id.hex()[:12]),)

    # ----------------------------------------------------------- lifecycle
    def start(self, labels: Optional[Dict[str, str]] = None,
              tcp_port: Optional[int] = None,
              advertise_host: str = "127.0.0.1") -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(128)
        if tcp_port is not None:
            # network plane: peers/drivers in other OS processes connect
            # here; the unix socket stays the local worker fast path
            self._tcp_listener = P.listen_tcp(port=tcp_port)
            self.tcp_address = (
                f"{advertise_host}:{self._tcp_listener.getsockname()[1]}")
            # Shared-memory capability probe: a driver that can read this
            # token back shares our /dev/shm and may use the shm data
            # plane; one that can't must ship payloads over the socket.
            # A direct probe beats hostname comparison (containers often
            # share names across machines).
            self.shm_probe_path = f"/dev/shm/rtpu_probe_{self.node_id.hex()[:12]}"
            self.shm_probe_token = os.urandom(8).hex()
            try:
                with open(self.shm_probe_path, "w") as f:
                    f.write(self.shm_probe_token)
            except OSError:
                self.shm_probe_path = None
        self.gcs.register_node(NodeInfo(
            node_id=self.node_id,
            address=self.tcp_address or self.socket_path,
            resources_total=dict(self.resources_total),
            labels=labels or {}, service=self, host=self.host,
            resources_available=dict(self.resources_total)))
        self.gcs.subscribe("OBJECT", self._on_object_published)
        self.gcs.subscribe("NODE", self._on_node_event)
        self.gcs.subscribe("TASK_FINISHED", self._on_task_finished)
        self.gcs.subscribe("ACTOR", self._on_actor_event)
        self.gcs.subscribe("REF_ZERO", self._on_ref_zero)
        self.gcs.subscribe("LOG", self._on_log_event)
        self.gcs.subscribe("GEN", self._on_gen_published)
        if CONFIG.log_to_driver:
            t_logs = threading.Thread(
                target=self._log_tail_loop,
                name=f"rtpu-logs-{self.node_id.hex()[:6]}", daemon=True)
            t_logs.start()
            self._threads.append(t_logs)
        t_acc = threading.Thread(target=self._accept_loop,
                                 args=(self._listener,),
                                 name=f"rtpu-accept-{self.node_id.hex()[:6]}",
                                 daemon=True)
        t_disp = threading.Thread(target=self._dispatch_loop,
                                  name=f"rtpu-dispatch-{self.node_id.hex()[:6]}",
                                  daemon=True)
        if self._tcp_listener is not None:
            t_tcp = threading.Thread(
                target=self._accept_loop, args=(self._tcp_listener,),
                name=f"rtpu-accept-tcp-{self.node_id.hex()[:6]}", daemon=True)
            t_tcp.start()
            self._threads.append(t_tcp)
        t_acc.start()
        t_disp.start()
        # Periodic tick: the dispatch loop otherwise only wakes on events,
        # so a worker that dies before ever connecting (e.g. a broken
        # runtime env) would leave its pending task asleep forever.
        t_tick = threading.Thread(target=self._tick_loop,
                                  name=f"rtpu-tick-{self.node_id.hex()[:6]}",
                                  daemon=True)
        t_tick.start()
        self._threads += [t_acc, t_disp, t_tick]
        # warm pool: spawning lazily on the first task burst serializes
        # behind worker cold-start (reference prestarts too,
        # ``worker_pool.h`` PrestartWorkers)
        n_pre = (CONFIG.num_prestart_workers
                 or int(self.resources_total.get("CPU", 0)))
        n_pre = max(0, min(n_pre, self._max_workers,
                           # leave startup-concurrency headroom so a
                           # runtime-env spawn isn't stuck behind the wave
                           CONFIG.maximum_startup_concurrency - 2))
        if n_pre:
            # Spawn ON the dispatcher thread: _spawn_worker mutates
            # dispatcher-owned state (_workers/_idle/_num_starting), and
            # the dispatcher is already live here — an early worker's
            # REGISTER (decrementing _num_starting) raced this loop's
            # `+= 1` on the main thread, and the lost update permanently
            # skewed the startup-concurrency budget (found by fieldsan,
            # ISSUE 15).
            self._events.put(("timer", lambda: [
                self._spawn_worker() for _ in range(n_pre)]))
        telemetry.attach_node(self)
        self.events.info("NODE_START", "node service started",
                         resources=dict(self.resources_total),
                         address=self.tcp_address or self.socket_path)

    def stop(self, kill_workers: bool = True,
             graceful: bool = True) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        self.dead = True
        telemetry.detach_node(self)
        try:
            self.gcs.remove_node(self.node_id, reason="node stopped")
        except Exception:   # remote GCS may already be gone
            pass
        for listener in (self._listener, self._tcp_listener):
            if listener is not None:
                try:
                    listener.close()
                except OSError:
                    pass
        if self.shm_probe_path:
            try:
                os.unlink(self.shm_probe_path)
            except OSError:
                pass
        for peer in list(self._peers.values()):
            peer.close()
        self._peers.clear()
        if graceful:
            # graceful-death announcement: workers drain queued
            # outbound frames (a TASK_DONE sitting in the writer queue)
            # and exit; drivers fail pending futures with "node
            # shutting down" instead of a bare connection-reset.
            # Skipped on the kill() chaos path, which must look like a
            # crash (reader EOF / heartbeat timeout), not a farewell.
            for conn in list(self._conns.values()):
                try:
                    conn.send((P.SHUTDOWN, ()))
                except OSError:
                    pass
        self._events.put(("stop",))
        if kill_workers:
            if graceful:
                # give workers a beat to act on the SHUTDOWN frame
                # (drain queued TASK_DONEs, close, exit) before the
                # SIGKILL below reaps stragglers — responsive workers
                # exit in single-digit ms, so this usually costs one
                # poll; the cap bounds a wedged worker's hold
                deadline = time.monotonic() + 0.25
                procs = [w.proc for w in self._workers.values()
                         if w.proc is not None]
                while (time.monotonic() < deadline
                       and any(p.poll() is None for p in procs)):
                    time.sleep(0.01)
            for w in list(self._workers.values()):
                if w.proc is not None:
                    try:
                        w.proc.kill()
                    except OSError:
                        pass
        for w in list(self._workers.values()):
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=5)
                except Exception:
                    pass
        self.store.shutdown()
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    def kill(self) -> None:
        """Simulate abrupt node failure (for chaos tests)."""
        self.stop(kill_workers=True, graceful=False)

    # ------------------------------------------------------ cross-thread API
    def available_snapshot(self) -> Dict[str, float]:
        with self._res_lock:
            return dict(self.resources_available)

    def reserve_bundle(self, pg_key: tuple, demand: Dict[str, float]) -> bool:
        with self._res_lock:
            if not sched.fits(self.resources_available, demand):
                return False
            sched.subtract(self.resources_available, demand)
            self.pg_reservations[pg_key] = dict(demand)
            self.pg_bundle_total[pg_key] = dict(demand)
            return True

    def release_bundle(self, pg_key: tuple) -> None:
        with self._res_lock:
            total = self.pg_bundle_total.pop(pg_key, None)
            self.pg_reservations.pop(pg_key, None)
            if total:
                sched.add(self.resources_available, total)

    def post_remote(self, item: tuple) -> None:
        """Called by peer node services / cluster utilities."""
        self._events.put(item)

    # ------------------------------------------------------------- threads
    def _accept_loop(self, listener) -> None:
        while not self._stopped.is_set():
            try:
                sock, _ = listener.accept()
            except OSError:
                return
            conn = P.Connection(sock)
            key = next(self._conn_keys)
            self._conns[key] = conn
            t = threading.Thread(target=self._reader_loop, args=(key, conn),
                                 daemon=True)
            t.start()

    # --------------------------------------------------------- log streaming
    def _log_tail_loop(self) -> None:
        """Tail THIS node's workers' logs and publish new lines
        cluster-wide (reference: ``python/ray/_private/log_monitor.py:103``).
        Every node forwards LOG events to its locally-connected drivers,
        so a ``print()`` in any remote task shows up on the driver's
        stdout. Only our own workers are tailed — in-process clusters
        share one session dir, and K nodes each tailing it would print
        every line K times (and replay history on scale-up)."""
        offsets: Dict[str, int] = {}
        labels: Dict[str, str] = {}
        quiet_since: Dict[str, float] = {}
        while not self._stopped.wait(0.25):
            workers = list(self._workers.values())
            live_paths = {w.log_path for w in workers if w.log_path}
            for w in workers:
                if w.log_path and w.log_label:
                    labels[w.log_path] = w.log_label
            # keep tailing files we've seen: a worker's last lines often
            # land right as it is reaped from self._workers — but prune
            # a DEAD worker's path once its file has been quiet for a
            # while (worker churn must not grow these dicts, or re-stat
            # every dead replica's log forever)
            paths = live_paths | set(offsets)
            now_t = time.monotonic()
            for path in paths:
                try:
                    size = os.path.getsize(path)
                    off = offsets.get(path, 0)
                    if size <= off:
                        if path not in live_paths:
                            first = quiet_since.setdefault(path, now_t)
                            if now_t - first > 30.0:
                                offsets.pop(path, None)
                                labels.pop(path, None)
                                quiet_since.pop(path, None)
                        continue
                    quiet_since.pop(path, None)
                    with open(path, "rb") as f:
                        f.seek(off)
                        data = f.read(min(size - off, 1 << 20))
                except OSError:
                    # file gone: nothing left to drain for it
                    if path not in live_paths:
                        offsets.pop(path, None)
                        labels.pop(path, None)
                        quiet_since.pop(path, None)
                    continue
                # consume only whole lines; a read landing mid-write
                # leaves the partial tail for the next poll
                consumed = data.rfind(b"\n") + 1
                if consumed == 0:
                    continue
                offsets[path] = off + consumed
                lines = data[:consumed].decode("utf-8", "replace"
                                               ).splitlines()
                worker = os.path.basename(path)[len("worker-"):-len(".log")]
                for i in range(0, len(lines), 200):
                    try:
                        self.gcs.publish("LOG", {
                            "node_id": self.node_id.hex()[:12],
                            "worker": worker,
                            "label": labels.get(path),
                            "lines": lines[i:i + 200],
                        })
                    except Exception:
                        break

    def _on_log_event(self, payload) -> None:
        """Forward worker log lines to locally-connected drivers."""
        for key in list(self._driver_conn_keys):
            self._reply(key, P.EVENT, ("LOG", payload))

    def _tick_loop(self) -> None:
        while True:
            # the memory monitor may need sub-second sampling to catch a
            # ballooning worker before the kernel OOM-killer does; the
            # other tick work tolerates running at the same faster cadence
            mm_period = CONFIG.memory_monitor_refresh_ms
            interval = min(1.0, mm_period / 1000.0) if mm_period > 0 else 1.0
            if self._stopped.wait(interval):
                return
            # Heartbeat from THIS thread, not the dispatcher: a slow peer
            # RPC can block the dispatcher past the GCS death deadline
            # (health period × threshold), and a healthy node must not be
            # declared dead because one transfer is slow.
            now_hb = time.monotonic()
            if now_hb - self._last_hb_at >= \
                    CONFIG.heartbeat_period_ms / 1000.0:
                self._last_hb_at = now_hb
                snap = self.available_snapshot()
                pend = self.pending_demand()
                # versioned delta sync (reference: ray_syncer.h:86):
                # ship the payload when the view changed, bumping the
                # monotonic version; every Nth beat is a full refresh
                # so a GCS that lost state (restart) converges even on
                # an otherwise-idle node
                self._hb_count += 1
                changed = (snap != self._last_hb_snapshot
                           or pend != self._last_hb_pending
                           or self._hb_count % 10 == 0)
                if changed:
                    self._resource_version += 1
                try:
                    self.gcs.heartbeat(
                        self.node_id,
                        snap if changed else None,
                        pending_shapes=pend if changed else None,
                        version=self._resource_version)
                except Exception:
                    # the payload did NOT land: leave the last-sent view
                    # unchanged so the next beat re-detects the delta
                    # and resends (committing early would drop it)
                    pass
                else:
                    if changed:
                        self._last_hb_snapshot = snap
                        self._last_hb_pending = pend
            self._events.put(("timer", self._on_tick))

    def _on_tick(self) -> None:
        self._reap_startup_failures()
        self._reap_idle_workers()
        self._check_memory_pressure()
        self._retry_infeasible()
        self._spill_starved_pending()
        self._rescue_stalled_waiters()
        self._sweep_stalls()
        self._sweep_object_leaks()
        self._drain_spill_events()
        self._record_metrics_history()
        # _dispatch fails pending tasks whose env exceeded the startup
        # failure budget (see the wid-None path)
        self._dispatch()

    # concurrency: dispatcher-only
    def _rescue_stalled_waiters(self) -> None:
        """Self-heal the readiness plane: a get/wait waiter whose object
        EXISTS can still be stranded — the register-time existence probe
        can transiently miss (a remote owner's store peek failing or
        timing out under load) AFTER the one OBJECT readiness event was
        already consumed, leaving nothing to ever fire the waiter. The
        tick re-probes waiters older than a beat with METADATA-ONLY
        evidence (local store / control-plane directory — no peer store
        RPC, so a tick stays cheap) and fires the ones that resolved;
        ``_fire_get``'s lookup still pulls or fails loudly."""
        if not self._get_waiters and not self._wait_waiters:
            return
        now = time.monotonic()
        # plane probes per tick (a remote node's directory lookup is an
        # RPC). A waiter too big for the REMAINING budget is skipped —
        # never `return` — so one huge get can't monopolize every tick
        # and starve a small stranded waiter behind it; oversized
        # waiters (> the whole budget) rely on the normal event flow
        # (the race this rescue closes strands few-oid waiters).
        budget = 256
        for waiter_id, waiter in (list(self._get_waiters.items())
                                  + list(self._wait_waiters.items())):
            if (now < waiter.probe_at or not waiter.remaining
                    or len(waiter.remaining) > budget):
                continue
            budget -= len(waiter.remaining)
            resolved = [oid for oid in waiter.remaining
                        if self._oid_rescuable(oid)]
            if not resolved:
                # nothing there yet (producer still running): back off
                # exponentially so steady-state cost per waiter decays
                waiter.probe_backoff = min(waiter.probe_backoff * 2, 30.0)
                waiter.probe_at = now + waiter.probe_backoff
                continue
            for oid in resolved:
                waiter.remaining.discard(oid)
                ids = self._obj_waiter_index.get(oid)
                if ids is not None:
                    ids.discard(waiter_id)
                    if not ids:
                        del self._obj_waiter_index[oid]
            self._maybe_fire_waiter(waiter_id, waiter)

    def _oid_rescuable(self, oid: ObjectID) -> bool:
        """Cheap existence evidence for the waiter rescue: our store,
        or a directory row (the object was sealed SOMEWHERE — for a
        task we own, only once the task finished, so a waiter on an
        in-flight retry is never fired early)."""
        if self.store.contains(oid):
            return True
        tid = TaskID(TaskID.KIND + oid.binary()[:15])
        owned = self._owned.get(tid)
        if owned is not None and not owned.done:
            return False        # still running: completion fires it
        try:
            return self.gcs.lookup_location(oid) is not None
        except Exception:       # noqa: BLE001 — plane hiccup: next tick
            return False

    def _sweep_stalls(self) -> None:
        """Trigger the control plane's stall detector. Only nodes
        hosting the plane in-process run it (in a networked cluster
        that's the head; remote nodes triggering over RPC would just
        race the head's sweep). The plane self-rate-limits, so the
        in-process multi-node case — every node sharing one plane —
        still sweeps once per interval."""
        if not isinstance(self.gcs, GlobalControlPlane):
            return
        try:
            stalls = self.gcs.maybe_sweep_stalls(
                coll_probe=self._coll_stall_probe)
        except Exception:   # noqa: BLE001 — diagnosis must not kill ticks
            return
        for rec in stalls:
            self.events.warning("TASK_STALL",
                                rec.pop("message", "task stalled"), **rec)

    def _sweep_object_leaks(self) -> None:
        """Trigger the control plane's object-leak sweep (same
        plane-hosting-node rule as ``_sweep_stalls``; the plane
        self-rate-limits). New findings become OBJECT_LEAK WARNING
        events carrying the creation callsite; the current finding
        count feeds the ``rtpu_object_leaked_objects`` gauge."""
        if not isinstance(self.gcs, GlobalControlPlane):
            return
        try:
            new, total = self.gcs.sweep_object_leaks()
        except Exception:   # noqa: BLE001 — diagnosis must not kill ticks
            return
        if total is not None:
            telemetry.gauge_set(telemetry.M_OBJ_LEAKED, float(total),
                                self._mtags)
        for rec in new:
            oid = rec.pop("object_id")
            # the object's LOCATION rides under its own key: **rec would
            # otherwise clobber EventLogger's standard node_id field
            # (the emitting node's hex) with a raw NodeID/None
            loc = rec.pop("node_id", None)
            where = (f" created at {rec['callsite']}" if rec.get("callsite")
                     else "")
            why = ("every ref holder lives on a dead node"
                   if rec.get("cause") == "dead_holders" else
                   f"pinned with zero holders for {rec.get('age_s', '?')}s")
            self.events.warning(
                "OBJECT_LEAK",
                f"object {oid.hex()[:12]}{where} looks leaked: {why}",
                object_id=oid.hex(),
                object_node_id=(loc.hex() if loc is not None else None),
                **rec)

    def _drain_spill_events(self) -> None:
        """Publish the store's spill/restore activity recorded since the
        last tick: byte counters for the doctor/bench planes plus
        attributed OBJECT_SPILLED / OBJECT_RESTORED cluster events — the
        spill carries the object's creation callsite from the PR-11
        provenance table when the plane is in-process. Runs outside the
        store lock by design (the store only queues; emitting under its
        lock would nest store.entries → gcs/telemetry locks)."""
        try:
            evts = self.store.drain_spill_events()
        except Exception:   # noqa: BLE001 — ticks must survive the store
            return
        if not evts:
            return
        spilled_bytes = sum(sz for kind, _, sz in evts if kind == "spill")
        restored = sum(1 for kind, _, _ in evts if kind == "restore")
        if spilled_bytes:
            telemetry.counter_inc(telemetry.M_OBJ_SPILLED_BYTES,
                                  float(spilled_bytes), self._mtags)
        if restored:
            telemetry.counter_inc(telemetry.M_OBJ_RESTORED,
                                  float(restored), self._mtags)
        prov: dict = {}
        if isinstance(self.gcs, GlobalControlPlane):
            try:
                prov = self.gcs.objects_info(
                    [oid for kind, oid, _ in evts if kind == "spill"])
            except Exception:   # noqa: BLE001 — events still emit bare
                prov = {}
        for kind, oid, size in evts:
            if kind == "spill":
                rec = prov.get(oid) or {}
                callsite = rec.get("callsite")
                where = f" created at {callsite}" if callsite else ""
                self.events.info(
                    "OBJECT_SPILLED",
                    f"object {oid.hex()[:12]} ({size} B){where} spilled "
                    f"to disk under memory pressure",
                    object_id=oid.hex(), size=size, callsite=callsite,
                    creator=(str(rec["creator"])
                             if rec.get("creator") else None))
            else:
                self.events.info(
                    "OBJECT_RESTORED",
                    f"object {oid.hex()[:12]} ({size} B) restored from "
                    f"its spill file on demand",
                    object_id=oid.hex(), size=size)

    def _record_metrics_history(self) -> None:
        """Tick-driven history snapshot: the plane-hosting node (same
        rule as the stall/leak sweeps — the plane self-rate-limits to
        its finest level step) flushes its own telemetry shards and
        appends one retention frame, then publishes the ring's byte
        footprint."""
        if not isinstance(self.gcs, GlobalControlPlane):
            return
        try:
            telemetry.maybe_flush(0.5)
            total = self.gcs.record_history_snapshot()
        except Exception:   # noqa: BLE001 — retention must not kill ticks
            return
        if total is not None:
            telemetry.gauge_set(history_mod.M_HISTORY_BYTES, float(total))

    def _coll_stall_probe(self, candidates: List[tuple]) -> List[tuple]:
        """``collective_stuck`` half of the stall sweep (runs on the
        tick thread, OUTSIDE the plane lock). Cheap pre-filter first:
        one COLL_PROGRESS fan-out — no stuck collective anywhere means
        no stack collection at all. Only when the diagnoser has a
        verdict do we collect cluster stacks and pair each candidate
        task (by the task_id its worker's dump now carries) with a
        thread parked in ``coll_transport.wait``."""
        verdicts = []
        try:
            report = self.collective_health(
                min(2.0, CONFIG.coll_progress_timeout_s), quiet=True)
            verdicts = report.get("verdicts") or []
        except Exception:   # noqa: BLE001 — diagnosis is best-effort
            return []
        if not verdicts:
            return []
        try:
            stacks = self._collect_nodes_debug(("stacks", 1.0), 1.0)
        except Exception:   # noqa: BLE001
            return []
        by_task = {}
        for dumps in stacks.values():
            for d in dumps or []:
                if d.get("task_id"):
                    by_task[d["task_id"]] = d
        # worker -> collective groups it belongs to, so a candidate gets
        # the verdict for ITS stuck group (two concurrently-stuck groups
        # must not cross-attribute their diagnoses)
        groups_of = {}
        for m in report.get("members", ()):
            if m.get("worker_id"):
                groups_of.setdefault(m["worker_id"], set()).add(
                    m["group"])
        out = []
        for ev, age in candidates:
            dump = by_task.get(ev.task_id.hex())
            if dump is None:
                continue
            in_coll = any(
                "coll_transport" in fr and "wait" in fr
                for th in dump.get("threads", ())
                for fr in th.get("frames", ()))
            if not in_coll:
                continue
            my_groups = groups_of.get(dump.get("worker_id"), set())
            matched = [v for v in verdicts if v.get("group") in my_groups]
            if not matched:
                # no verdict for THIS task's groups: it is not stuck in
                # a diagnosed collective — never cross-attribute another
                # group's diagnosis
                continue
            verdict_msg = matched[0].get(
                "message", "see state.collective_health()")
            out.append((ev, "collective_stuck",
                        f"task {ev.name!r} has been parked in a "
                        f"collective wait for {age:.0f}s (past "
                        f"collective_timeout_s/2) — {verdict_msg}"))
        return out

    def _check_memory_pressure(self) -> None:
        """Kill one worker per check while above the usage threshold
        (reference: memory_monitor.h:52 + worker_killing_policy.h:34)."""
        period = CONFIG.memory_monitor_refresh_ms
        if period <= 0:
            return
        now = time.monotonic()
        if now - self._last_mem_check < period / 1000.0:
            return
        self._last_mem_check = now
        frac = self._memory_monitor.usage_fraction()
        if frac < CONFIG.memory_usage_threshold:
            return
        victim = memory_monitor.pick_oom_victim(
            self._workers.values(),
            # restarts_left == -1 means restart forever (same contract as
            # the restart path below): that actor is maximally retriable
            actor_restartable=lambda aid: (
                (self._actors.get(aid) or {}).get("restarts_left", 0) != 0),
            # among equally-retriable candidates kill the biggest RSS:
            # that is the kill that actually relieves the pressure
            rss_of=lambda w: memory_monitor.process_rss_bytes(
                w.proc.pid if w.proc is not None else (w.pid or -1)))
        if victim is None:
            return
        pid = victim.proc.pid if victim.proc is not None else victim.pid
        if pid is None:
            # externally-registered worker we cannot signal: killing only
            # its connection would leave the process running (no memory
            # freed, task double-executes on retry)
            return
        victim.oom_victim = True
        snap = self._memory_monitor.snapshot()
        rss = memory_monitor.process_rss_bytes(pid)
        top = self._oom_autopsy(victim)
        print(f"[rtpu] node {self.node_id.hex()[:8]}: memory usage "
              f"{frac:.0%} >= threshold "
              f"{CONFIG.memory_usage_threshold:.0%}; killing worker "
              f"pid={pid} ({snap['available_bytes']>>20} MiB avail)",
              file=sys.stderr)
        # autopsy in the event itself: the victim's RSS plus the top
        # objects it owned/held, each with its creation callsite — the
        # kill names its probable cause instead of a bare OOM_KILL
        message = ("memory monitor killed a worker to relieve node "
                   f"memory pressure (victim rss {rss >> 20} MiB)")
        if top:
            t0 = top[0]
            where = (f", created at {t0['callsite']}" if t0.get("callsite")
                     else "")
            message += (f"; top held object {t0['object_id'][:12]} "
                        f"({t0.get('size') or '?'} B{where})")
        self.events.warning(
            "OOM_KILL", message, pid=pid,
            usage_fraction=round(frac, 3),
            rss_bytes=rss,
            top_objects=top,
            task=(victim.task.spec.name if victim.task else None),
            actor_id=(victim.actor_id.hex() if victim.actor_id else None))
        # a kill under memory pressure is a terminal event worth a
        # corpse: capture a post-mortem bundle off-thread (the tick
        # must not stall on the stack/flight-record fan-outs)
        from . import debug_bundle
        debug_bundle.auto_capture("oom_kill", node=self,
                                  fields={"victim_pid": pid},
                                  background=True)
        try:
            if victim.proc is not None:
                victim.proc.kill()
            else:
                os.kill(pid, signal.SIGKILL)
        except OSError:
            pass

    def _oom_autopsy(self, victim) -> List[dict]:
        """Top objects the OOM victim owned/held: refs registered on its
        connection plus the resolved args of its running/pipelined
        tasks, sized and attributed through the control plane in one
        ``objects_info`` batch. Best-effort and bounded — the kill must
        not wait on a slow plane."""
        oids: List[ObjectID] = []
        seen = set()
        if victim.conn_key is not None:
            for oid in list(self._conn_refs.get(victim.conn_key) or ()):
                if oid not in seen:
                    seen.add(oid)
                    oids.append(oid)
        for rec in (([victim.task] if victim.task is not None else [])
                    + list(victim.pipeline)):
            for oid in rec.deps:
                if oid not in seen:
                    seen.add(oid)
                    oids.append(oid)
        if not oids:
            return []
        try:
            info = self.gcs.objects_info(oids[:64])
        except Exception:   # noqa: BLE001 — autopsy is best-effort
            return []
        rows = sorted(info.values(),
                      key=lambda r: -(r.get("size") or 0))[:5]
        return [{"object_id": r["object_id"].hex(),
                 "size": r.get("size"),
                 "callsite": r.get("callsite"),
                 "creator": r.get("creator")} for r in rows]

    def _park_infeasible(self, kind: str, spec) -> bool:
        """Queue work with no feasible node while the autoscaler adds
        capacity; False when fail-fast semantics apply (grace 0)."""
        grace = CONFIG.infeasible_task_grace_s
        if grace <= 0:
            return False
        deadline = (self._repark_deadline if self._repark_deadline
                    is not None else time.monotonic() + grace)
        self._infeasible.append((deadline, kind, spec))
        return True

    def _fail_actor_infeasible(self, spec: P.ActorSpec) -> None:
        self.gcs.set_actor_state(spec.actor_id, ACTOR_DEAD,
                                 reason="no feasible node")
        if spec.creation_return_id:
            err = to_bytes(exceptions.ActorDiedError(
                spec.actor_id, "no feasible node for actor resources"))
            self._seal_object(ObjectMeta(
                object_id=spec.creation_return_id, size=len(err),
                error=err))

    def _retry_infeasible(self) -> None:
        if not self._infeasible:
            return
        parked, self._infeasible = self._infeasible, []
        now = time.monotonic()
        for deadline, kind, spec in parked:
            if self._probe_target(spec) is not None:
                # keep the original deadline if routing re-parks (the
                # cluster changed between probe and route)
                self._repark_deadline = deadline
                try:
                    if kind == "task":
                        self._route_task(spec)
                    else:
                        self._route_actor(spec)
                finally:
                    self._repark_deadline = None
            elif now < deadline:
                self._infeasible.append((deadline, kind, spec))
            elif kind == "task":
                self._record_event(spec, "FAILED")
                self._fail_returns(spec, RuntimeError(
                    f"no feasible node for resources {spec.resources} "
                    f"within {CONFIG.infeasible_task_grace_s}s"))
            else:
                self._fail_actor_infeasible(spec)

    def pending_demand(self) -> List[Dict[str, float]]:
        """Queued-but-unplaced resource shapes (autoscaler input)."""
        shapes: List[Dict[str, float]] = []
        try:
            for rec in list(self._pending)[:100]:
                shapes.append(dict(rec.spec.resources))
            for _, kind, spec in list(self._infeasible)[:100]:
                shapes.append(dict(spec.resources))
        except RuntimeError:   # racy snapshot from the tick thread
            pass
        return shapes

    # Ops answered inline on the connection-reader thread. The object
    # plane and bundle reservation are thread-safe (store RLock /
    # _res_lock) and MUST NOT wait on the dispatcher: peer A's
    # dispatcher may be blocked on a request to B while B's is blocked
    # on a request to A. Puts (alloc/seal) are also served here so a
    # 100MB memcpy-heavy put stream never queues behind task dispatch —
    # the same separation the reference gets from plasma being its own
    # process.
    _DIRECT_OPS = frozenset({P.NODE_POST, P.OBJ_GET_META, P.OBJ_UNPIN,
                             P.OBJ_PULL_CHUNK, P.PG_RESERVE,
                             P.PG_RELEASE, P.NODE_STATS, P.ALLOC_OBJECT,
                             P.PUT_OBJECT, P.PUT_OBJECT_SYNC,
                             P.PUT_OBJECT_WIRE,
                             # debug plane: replies resolve futures and
                             # collection requests spawn their own
                             # thread, so neither may queue behind (or
                             # block) the dispatcher
                             P.STACK_REPLY, P.PROFILE_REPORT,
                             P.CLUSTER_STACKS, P.CLUSTER_PROFILE,
                             P.COLL_PROGRESS_REPLY, P.CLUSTER_COLL,
                             # collective chunks are data plane: routed
                             # on the arrival reader thread so a ring
                             # step never queues behind task dispatch
                             P.COLL_ROUTE, P.COLL_FWD})

    def _reader_loop(self, key: int, conn: P.Connection) -> None:
        while True:
            # burst receive: every frame the peer's writer coalesced is
            # decoded in one wakeup; non-direct messages post to the
            # dispatcher as ONE event so a 100-frame burst is one
            # scheduling pass, not 100 queue round-trips
            msgs = conn.recv_many()
            if msgs is None:
                self._events.put(("conn_closed", key))
                return
            queued: Optional[List[tuple]] = None
            for msg in msgs:
                if msg[0] in self._DIRECT_OPS:
                    try:
                        self._handle_direct(key, *msg)
                    except Exception:
                        import traceback
                        traceback.print_exc(file=sys.stderr)
                        # request-type ops carry (req_id, ...): answer so
                        # the caller doesn't block out its full timeout
                        op, payload = msg
                        if op in (P.OBJ_GET_META, P.OBJ_PULL_CHUNK,
                                  P.PG_RESERVE, P.NODE_STATS,
                                  P.ALLOC_OBJECT, P.CLUSTER_STACKS,
                                  P.CLUSTER_PROFILE, P.CLUSTER_COLL
                                  ) and isinstance(payload, tuple):
                            result = False if op == P.PG_RESERVE else None
                            self._reply(key, P.INFO_REPLY,
                                        (payload[0], result))
                        elif (op in (P.PUT_OBJECT_SYNC, P.PUT_OBJECT_WIRE)
                              and isinstance(payload, tuple)):
                            err = to_bytes(RuntimeError(
                                "put failed on the node store"))
                            self._reply(key, P.ERROR_REPLY,
                                        (payload[0], err))
                else:
                    if queued is None:
                        queued = []
                    queued.append(msg)
            if queued:
                self._events.put(("msgs", key, queued))

    def _handle_direct(self, key: int, op: int, payload: Any) -> None:
        if op == P.NODE_POST:
            self._events.put(tuple(payload))
        elif op in (P.COLL_ROUTE, P.COLL_FWD):
            dst_node, dst_wid, coll_key, data = payload
            self._coll_route(dst_node, dst_wid, coll_key, data)
        elif op == P.OBJ_GET_META:
            req_id, oid, pin = payload
            meta = (self.store.pin_and_get(oid) if pin
                    else self.store.get_meta(oid))
            self._reply(key, P.INFO_REPLY, (req_id, meta))
        elif op == P.OBJ_UNPIN:
            self.store.unpin(payload)
        elif op == P.OBJ_PULL_CHUNK:
            req_id, oid, offset, length = payload
            res = self.store.read_payload_chunk(oid, offset, length)
            if res is not None and res[1] is not None:
                # chunk bytes ride out-of-band: straight from the store
                # copy to the socket as an iovec, no pickle-stream copy
                res = (res[0], P.oob_wrap(res[1]))
            self._reply(key, P.INFO_REPLY, (req_id, res))
        elif op == P.PG_RESERVE:
            req_id, pg_key, demand = payload
            self._reply(key, P.INFO_REPLY,
                        (req_id, self.reserve_bundle(tuple(pg_key), demand)))
        elif op == P.PG_RELEASE:
            self.release_bundle(tuple(payload))
        elif op == P.NODE_STATS:
            req_id, what = payload
            if isinstance(what, tuple):
                # debug collections ("stacks"/"profile") block for up to
                # their timeout waiting on worker replies; a dedicated
                # thread keeps this peer channel's reader serving object
                # pulls meanwhile
                self._spawn_debug_reply(key, req_id,
                                        lambda w=what: self.node_stats(w))
            else:
                self._reply(key, P.INFO_REPLY,
                            (req_id, self.node_stats(what)))  # lint: allow-on-reader(non-tuple whats are pure snapshots; the blocking tuple forms take the _spawn_debug_reply thread above)
        elif op in (P.STACK_REPLY, P.PROFILE_REPORT,
                    P.COLL_PROGRESS_REPLY):
            token, data = payload
            with self._debug_lock:
                fut = self._debug_futures.pop(token, None)
            if fut is not None and not fut.done():
                fut.set_result(data)
        elif op == P.CLUSTER_COLL:
            req_id, what, timeout_s = payload
            self._spawn_debug_reply(
                key, req_id,
                lambda w=what, t=timeout_s: (
                    self.collective_health(float(t)) if w == "health"
                    else self.collect_flight_records(float(t))))
        elif op == P.CLUSTER_STACKS:
            req_id, timeout_s = payload
            self._spawn_debug_reply(
                key, req_id,
                lambda t=timeout_s: self.cluster_stacks(float(t)))
        elif op == P.CLUSTER_PROFILE:
            req_id, opts = payload
            self._spawn_debug_reply(
                key, req_id,
                lambda o=opts: self.cluster_profile(dict(o or {})))
        elif op == P.ALLOC_OBJECT:
            req_id, oid, size = payload
            try:
                ref = self.store.alloc_in_arena(oid, size, writer_tag=key)
            except Exception:   # noqa: BLE001 — client blocks on a reply
                ref = None
            self._reply(key, P.INFO_REPLY, (req_id, ref))
        elif op == P.PUT_OBJECT:
            self._seal_object(payload)
        elif op == P.PUT_OBJECT_SYNC:
            req_id, meta = payload
            try:
                self._seal_object(meta)
            except Exception as e:  # noqa: BLE001 — client put() blocks
                self._reply(key, P.ERROR_REPLY, (req_id, to_bytes(e)))
            else:
                self._reply(key, P.PUT_REPLY, (req_id,))
        elif op == P.PUT_OBJECT_WIRE:
            # cross-host driver put: the payload arrived over the socket
            # (a zero-copy out-of-band view into the frame buffer for
            # large transfers); land it straight in an arena block /
            # segment as the primary copy — one copy off the socket
            req_id, oid, data = payload
            try:
                meta = self.store.put_payload(oid, data)
                # adopt already ran inside put_payload; _seal_object's
                # re-adopt is a no-op and it publishes the location
                self._seal_object(meta)
            except Exception as e:  # noqa: BLE001 — client put() blocks
                self._reply(key, P.ERROR_REPLY, (req_id, to_bytes(e)))
            else:
                self._reply(key, P.PUT_REPLY, (req_id,))

    def _coll_route(self, dst_node: bytes, dst_wid: bytes, coll_key,
                    data) -> None:
        """Deliver one collective chunk: to a local process's conn when
        the destination endpoint lives here, else across the node plane.
        Runs on reader threads (data plane — never the dispatcher).
        Fire and forget: an unroutable chunk (dead process/node) is
        dropped and surfaces as the receiving rank's deadline."""
        if dst_node == self.node_id.binary():
            conn = self._coll_conns.get(dst_wid)
            if conn is None:
                return
            try:
                conn.send((P.COLL_DELIVER, (coll_key, data)))
            except OSError:
                pass
            return
        peer = self._coll_peers.get(dst_node)
        if peer is not None and (peer.closed if isinstance(peer, _RemotePeer)
                                 else peer.dead):
            peer = None
        if peer is None:
            peer = self._peer(NodeID(dst_node))  # lint: allow-on-reader(one gcs.get_node RPC per peer-lifetime cache miss; steady-state chunks hit _coll_peers — PR5's documented tradeoff)
            if peer is None:
                return
            self._coll_peers[dst_node] = peer
        if isinstance(peer, NodeService):
            peer._coll_route(dst_node, dst_wid, coll_key, data)
        else:
            peer.coll_forward((dst_node, dst_wid, coll_key, data))

    def node_stats(self, what) -> Any:
        """Cross-thread node introspection (also served to peers).
        Tuple forms carry arguments: ``("stacks", timeout_s)`` and
        ``("profile", opts)`` are this node's debug-collection surface
        for remote peers."""
        if isinstance(what, tuple) and what:
            if what[0] == "stacks":
                return self.collect_local_stacks(float(what[1]))
            if what[0] == "profile":
                return self.collect_local_profile(dict(what[1] or {}))
            if what[0] == "coll":
                return self.collect_local_coll_progress(float(what[1]))
            return None
        if what == "available":
            return self.available_snapshot()
        if what == "store":
            return self.store.stats()
        if what == "workers":
            for _ in range(3):   # dict may be mutated by the dispatcher
                try:
                    return [{
                        "worker_id": wid.hex(),
                        "node_id": self.node_id.hex(),
                        "pid": w.proc.pid if w.proc else None,
                        "state": w.state,
                        "actor_id": (w.actor_id.hex()
                                     if w.actor_id else None),
                    } for wid, w in list(self._workers.items())]
                except RuntimeError:
                    continue
            return []
        if what == "memory":
            return self._memory_monitor.snapshot()
        if what == "objects":
            # per-object (pinned, spilled) from THIS node's store — the
            # node-local half of the memory introspection plane
            return self.store.objects_snapshot()
        return None

    # -------------------------------------------- debugging & profiling
    # Reference analogues: `ray stack` (py-spy over every worker pid)
    # and the profiling hooks. Here: STACK_DUMP/PROFILE_START frames fan
    # out to every locally-connected worker/driver; replies resolve
    # futures on each connection's reader thread, so a process blocked
    # in user code (even in get()) still reports.

    def _spawn_debug_reply(self, key: int, req_id: int, fn) -> None:
        """Serve a blocking debug collection off the reader thread."""
        def run():
            try:
                result = fn()
            except Exception:   # noqa: BLE001 — debugging is best-effort
                result = None
            self._reply(key, P.INFO_REPLY, (req_id, result))
        threading.Thread(target=run, daemon=True,
                         name="rtpu-debug-collect").start()

    def _debug_fanout(self, targets: List[tuple], op: int,
                      make_payload) -> List[tuple]:
        """Send one debug frame per target conn; returns [(future,
        extra), ...] for the sends that left."""
        waits = []
        for conn, extra in targets:
            with self._debug_lock:
                token = self._next_debug_token
                self._next_debug_token += 1
                fut: Future = Future()
                self._debug_futures[token] = fut
            try:
                conn.send((op, make_payload(token)))
            except OSError:
                with self._debug_lock:
                    self._debug_futures.pop(token, None)
                continue
            waits.append((token, fut, extra))
        return waits

    def _debug_collect(self, waits: List[tuple],
                       timeout_s: float) -> List[Any]:
        out = []
        deadline = time.monotonic() + timeout_s
        for token, fut, extra in waits:
            try:
                data = fut.result(
                    timeout=max(0.05, deadline - time.monotonic()))
            except Exception:   # timeout / conn died mid-collection
                with self._debug_lock:
                    self._debug_futures.pop(token, None)
                continue
            if isinstance(data, dict):
                for k, v in extra.items():
                    data.setdefault(k, v)
                out.append(data)
        return out

    def collect_local_stacks(self, timeout_s: float = 2.0) -> List[dict]:
        """Thread dumps of this node process + every locally-connected
        worker and driver."""
        from . import debugging
        node_hex = self.node_id.hex()[:12]
        dumps = [debugging.collect_stack_dump(kind="node",
                                              node_id=node_hex)]
        targets = []
        for w in list(self._workers.values()):
            if w.conn is not None:
                targets.append((w.conn, {"node_id": node_hex}))
        for key in list(self._driver_conn_keys):
            conn = self._conns.get(key)
            if conn is not None:
                targets.append((conn, {"node_id": node_hex}))
        waits = self._debug_fanout(targets, P.STACK_DUMP, lambda t: t)
        dumps.extend(self._debug_collect(waits, timeout_s))
        return dumps

    def collect_local_profile(self, opts: dict) -> List[dict]:
        """Start the sampling profiler in every local worker; block
        until their reports arrive (bounded by the capped duration)."""
        duration = min(float(opts.get("duration_s") or 5.0),
                       CONFIG.profiler_max_duration_s)
        opts = {**opts, "duration_s": duration}
        opts.setdefault("interval_ms", CONFIG.profiler_default_interval_ms)
        node_hex = self.node_id.hex()[:12]
        targets = [(w.conn, {"node_id": node_hex,
                             "worker_id": w.worker_id.hex()})
                   for w in list(self._workers.values())
                   if w.conn is not None]
        waits = self._debug_fanout(targets, P.PROFILE_START,
                                   lambda t: (t, opts))
        return self._debug_collect(waits, duration + 10.0)

    def collect_local_coll_progress(self, timeout_s: float = 2.0
                                    ) -> List[dict]:
        """Flight-recorder progress snapshots of every locally-connected
        worker AND driver (a driver can be a collective rank). Replies
        arrive on each process's reader thread — a rank wedged inside
        the collective being diagnosed still answers."""
        node_hex = self.node_id.hex()[:12]
        targets = []
        for w in list(self._workers.values()):
            if w.conn is not None:
                targets.append((w.conn, {"node_id": node_hex}))
        for key in list(self._driver_conn_keys):
            conn = self._conns.get(key)
            if conn is not None:
                targets.append((conn, {"node_id": node_hex}))
        waits = self._debug_fanout(targets, P.COLL_PROGRESS, lambda t: t)
        return self._debug_collect(waits, timeout_s)

    def _collect_cluster_coll(self, timeout_s: float) -> Dict[str, Any]:
        return {hexid: snaps or []
                for hexid, snaps in self._collect_nodes_debug(
                    ("coll", timeout_s), timeout_s).items()}

    def collective_health(self, timeout_s: Optional[float] = None,
                          quiet: bool = False) -> dict:
        """Cluster-wide collective hang & straggler diagnosis: collect
        every rank's flight-recorder watermarks, diff them, and name
        the verdict per stuck op — dead rank, lost chunk, or lagging
        rank (with the lagging rank's current thread stack attached
        from a PR-2 stack dump when one can be matched)."""
        from . import flight_recorder
        cached_at, cached = self._coll_health_cache
        if cached is not None and time.monotonic() - cached_at < 1.0:
            return cached
        t = timeout_s if timeout_s is not None \
            else CONFIG.coll_progress_timeout_s
        per_node = self._collect_cluster_coll(t)
        report = flight_recorder.diagnose(per_node)
        lagging = [v for v in report.get("verdicts", ())
                   if v.get("verdict") == "lagging_rank"]
        if lagging:
            self._attach_lagging_stacks(report, lagging, per_node)
        if not quiet:
            self.events.info(
                "DEBUG_COLLECTIVES",
                "collected cluster-wide collective health",
                ops=len(report.get("ops", ())),
                verdicts=len(report.get("verdicts", ())))
        self._coll_health_cache = (time.monotonic(), report)
        return report

    def _attach_lagging_stacks(self, report: dict, lagging: List[dict],
                               per_node: Dict[str, Any]) -> None:
        """Best-effort: name WHERE each lagging rank is stuck by pairing
        its endpoint with a cluster stack dump."""
        # rank -> worker hex prefix, from any snapshot's group registry
        eps: Dict[tuple, list] = {}
        for snaps in per_node.values():
            for s in snaps or []:
                for g in s.get("groups", ()):
                    if g.get("endpoints"):
                        eps[(g["group"], g["epoch"])] = g["endpoints"]
        try:
            stacks = self._collect_nodes_debug(("stacks", 1.0), 1.0)
        except Exception:   # noqa: BLE001 — stacks are garnish
            return
        dumps = [d for ds in stacks.values() for d in ds or []]
        for v in lagging:
            group_eps = eps.get((v["group"], v["epoch"])) or []
            ep = (group_eps[v["rank"]]
                  if 0 <= v["rank"] < len(group_eps) else None)
            if not ep:
                continue
            for d in dumps:
                wid = d.get("worker_id") or ""
                if not wid.startswith(ep[1]):
                    continue
                th = next(
                    (t for t in d.get("threads", ())
                     if any("coll_transport" in fr
                            for fr in t.get("frames", ()))),
                    None) or next(
                    (t for t in d.get("threads", ())
                     if t.get("thread_name") == "task-exec"), None)
                if th is not None:
                    v["stack"] = list(th.get("frames", ()))
                break

    def collect_flight_records(self, timeout_s: Optional[float] = None
                               ) -> dict:
        """Every process's raw flight-recorder snapshot (recent event
        ring + completed-op records), keyed by node."""
        t = timeout_s if timeout_s is not None \
            else CONFIG.coll_progress_timeout_s
        return {"nodes": self._collect_cluster_coll(t)}

    def _collect_nodes_debug(self, what: tuple,
                             timeout_s: float) -> Dict[str, Any]:
        """Fan a debug collection out to every alive node (in-process
        shortcut or peer RPC) CONCURRENTLY: sequential collection would
        stack per-node timeouts AND give each node a disjoint sampling
        window — cross-node straggler comparison needs one window."""
        results: Dict[str, Any] = {}

        def one(info, hexid):
            try:
                results[hexid] = self._peer_stats(
                    info, what, timeout=timeout_s + 15.0)
            except Exception:   # noqa: BLE001 — a dead peer is a gap
                results[hexid] = None

        threads = []
        for info in self.gcs.alive_nodes():
            hexid = info.node_id.hex()[:12]
            results[hexid] = None    # visible even if its thread hangs
            t = threading.Thread(target=one, args=(info, hexid),
                                 daemon=True, name="rtpu-debug-node")
            t.start()
            threads.append(t)
        deadline = time.monotonic() + timeout_s + 20.0
        for t in threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))
        return results

    def cluster_stacks(self, timeout_s: float = 5.0) -> dict:
        """Cluster-wide `rtpu stack`: every node's dumps, deduplicated
        by the control plane (``gcs.aggregate_stacks``)."""
        from .gcs import aggregate_stacks
        per_node = {hexid: dumps or []
                    for hexid, dumps in self._collect_nodes_debug(
                        ("stacks", timeout_s), timeout_s).items()}
        n_procs = sum(len(d) for d in per_node.values())
        self.events.info("DEBUG_STACKS",
                         "collected cluster-wide stack dump",
                         nodes=len(per_node), processes=n_procs)
        return {"nodes": per_node, "groups": aggregate_stacks(per_node)}

    def cluster_profile(self, opts: dict) -> dict:
        """Cluster-wide sampling profile: every node's worker reports
        plus merged collapsed stacks. All nodes sample the SAME window
        (concurrent fan-out)."""
        from . import debugging
        duration = min(float(opts.get("duration_s") or 5.0),
                       CONFIG.profiler_max_duration_s)
        per_node = {}
        reports: List[dict] = []
        for hexid, reps in self._collect_nodes_debug(
                ("profile", {**opts, "duration_s": duration}),
                duration + 15.0).items():
            per_node[hexid] = reps or []
            reports.extend(reps or [])
        collapsed = debugging.merge_collapsed(reports)
        self.events.info("DEBUG_PROFILE",
                         "collected cluster-wide sampling profile",
                         duration_s=duration, workers=len(reports),
                         stacks=len(collapsed))
        return {"nodes": per_node, "collapsed": collapsed,
                "duration_s": duration,
                "num_samples": sum(r.get("num_samples", 0)
                                   for r in reports)}

    def _dispatch_loop(self) -> None:
        while True:
            item = self._events.get()
            # Drain everything already queued: a burst of events (many
            # TASK_DONEs, object seals, submissions from several conns)
            # is handled with ONE scheduling pass and one outbox flush,
            # not one per event — the cross-event extension of the
            # SUBMIT_BATCH burst hook. Bounded so ticks/outbox flushes
            # keep their cadence under sustained load.
            batch: Optional[list] = None
            budget = CONFIG.dispatcher_event_batch - 1
            while budget > 0:
                try:
                    nxt = self._events.get_nowait()
                except queue.Empty:
                    break
                if batch is None:
                    batch = [item]
                batch.append(nxt)
                budget -= 1
            if batch is None:
                if item[0] == "stop":
                    return
                try:
                    self._handle(item)
                except Exception:
                    import traceback
                    traceback.print_exc(file=sys.stderr)
                finally:
                    self._flush_outboxes()
                continue
            stop = False
            prev = self._in_batch
            self._in_batch = True
            try:
                for it in batch:
                    if it[0] == "stop":
                        stop = True
                        break
                    try:
                        self._handle(it)
                    except Exception:
                        import traceback
                        traceback.print_exc(file=sys.stderr)
            finally:
                self._in_batch = prev
            if not stop:
                try:
                    self._dispatch()
                except Exception:
                    import traceback
                    traceback.print_exc(file=sys.stderr)
            self._flush_outboxes()
            if stop:
                return

    def _send_execute(self, w: _Worker, item: tuple) -> None:
        """Queue an EXECUTE for this worker; coalesced per event."""
        self._exec_outbox.setdefault(w.worker_id, []).append(item)

    # concurrency: dispatcher-only
    def _flush_outboxes(self) -> None:
        if self._exec_outbox:
            self._flush_exec_outbox()
        if self._reply_outbox:
            self._flush_reply_outbox()

    def _flush_exec_outbox(self) -> None:
        outbox, self._exec_outbox = self._exec_outbox, {}
        for wid, items in outbox.items():
            w = self._workers.get(wid)
            if w is None or w.conn is None:
                continue
            try:
                if len(items) == 1:
                    w.conn.send((P.EXECUTE_TASK, items[0]))
                else:
                    w.conn.send((P.EXECUTE_BATCH, items))
            except OSError:
                self._events.put(("conn_closed", w.conn_key))

    # concurrency: dispatcher-only
    def _reply_batched(self, conn_key: int, op: int, payload: Any) -> None:
        """Reply from a DISPATCHER-thread path: buffered per connection
        and flushed as one ordered burst at the end of the current event
        batch — a storm of GET_REPLYs costs the client one frame and one
        reader wakeup instead of one each. Zero added latency: the flush
        happens before the dispatcher sleeps again. Reader/debug threads
        must keep using _reply (direct, thread-safe)."""
        self._reply_outbox.setdefault(conn_key, []).append((op, payload))

    def _flush_reply_outbox(self) -> None:
        outbox, self._reply_outbox = self._reply_outbox, {}
        for key, msgs in outbox.items():
            conn = self._conns.get(key)
            if conn is None:
                continue
            try:
                conn.send_many(msgs)
            except OSError:
                pass

    # ------------------------------------------------------------- handling
    # concurrency: dispatcher-only
    def _handle(self, item: tuple) -> None:
        kind = item[0]
        if kind == "msg":
            _, key, (op, payload) = item
            self._handle_msg(key, op, payload)
        elif kind == "msgs":
            self._handle_burst(item[1], item[2])
        elif kind == "conn_closed":
            self._on_conn_closed(item[1])
        elif kind == "remote_task":
            self._queue_local(item[1], "task")
        elif kind == "remote_actor_create":
            self._local_create_actor(item[1])
        elif kind == "remote_actor_task":
            self._local_actor_task(item[1])
        elif kind == "remote_kill_actor":
            self._local_kill_actor(item[1], item[2])
        elif kind == "remote_cancel":
            self._local_cancel(item[1], item[2])
        elif kind == "object_ready":
            self._on_object_ready(item[1], item[2])
        elif kind == "node_dead":
            self._on_node_dead(item[1])
        elif kind == "task_finished":
            owned = self._owned.pop(item[1], None)
            if owned is not None:
                # we were the submitter: release the task's arg pins
                try:
                    self.gcs.unpin_task_args(item[1])
                except Exception:
                    pass
        elif kind == "ref_zero":
            self._local_ref_zero(item[1], item[2])
        elif kind == "actor_dead":
            self._on_remote_actor_dead(item[1], item[2])
        elif kind == "actor_reroute":
            self._reroute_actor(item[1])
        elif kind == "actor_parked_flush":
            self._flush_parked_actor_calls(item[1])
        elif kind == "spillback_task":
            self._on_spillback_task(item[1], item[2])
        elif kind == "gen_event":
            self._on_gen_event(item[1])
        elif kind == "timer":
            item[1]()

    # concurrency: dispatcher-only
    def _handle_burst(self, key: int, msgs: List[tuple]) -> None:
        """One receive burst from one connection, handled with a single
        scheduling pass at the end (mirrors SUBMIT_BATCH): a burst of
        TASK_DONEs frees N workers then dispatches once, not N times."""
        if len(msgs) == 1:
            self._handle_msg(key, *msgs[0])
            return
        prev = self._in_batch
        self._in_batch = True
        try:
            for op, payload in msgs:
                try:
                    self._handle_msg(key, op, payload)
                except Exception:
                    import traceback
                    traceback.print_exc(file=sys.stderr)
        finally:
            self._in_batch = prev
        if not self._in_batch:
            self._dispatch()

    # concurrency: dispatcher-only
    def _handle_msg(self, key: int, op: int, payload: Any) -> None:
        if op == P.REGISTER:
            kind, worker_id, pid = payload
            self._conn_kind[key] = kind
            # collective endpoint route for this process (drivers too)
            self._coll_conns[bytes(worker_id)] = self._conns[key]
            self._conn_coll_wid[key] = bytes(worker_id)
            if kind == P.KIND_WORKER:
                wid = WorkerID(worker_id)
                self._conn_worker[key] = wid
                w = self._workers.get(wid)
                if w is None:
                    w = _Worker(worker_id=wid)
                    self._workers[wid] = w
                w.conn = self._conns[key]
                w.conn_key = key
                w.pid = pid
                self._num_starting = max(0, self._num_starting - 1)
                self._env_spawn_failures.pop(w.env_key, None)
                if w.state == "STARTING":
                    self._mark_idle(w)
                if not self._in_batch:
                    self._dispatch()
            else:
                self._driver_conn_keys.add(key)
        elif op == P.SUBMIT_TASK:
            self._submit_task(payload)
        elif op == P.SUBMIT_BATCH:
            # coalesced submissions: queue them all, then dispatch once —
            # a 100-task burst is one scheduling pass, not 100.
            # Save/restore: this frame may itself arrive inside a
            # transport burst (_handle_burst) that defers the dispatch.
            telemetry.hist_observe(telemetry.M_SUBMIT_BATCH,
                                   float(len(payload)), self._mtags)
            prev = self._in_batch
            self._in_batch = True
            try:
                for sub_op, spec in payload:
                    self._handle_msg(key, sub_op, spec)
            finally:
                self._in_batch = prev
            if not self._in_batch:
                self._dispatch()
        elif op == P.CREATE_ACTOR:
            self._create_actor(payload)
        elif op == P.SUBMIT_ACTOR_TASK:
            self._submit_actor_task(payload)
        elif op == P.NOTIFY_BLOCKED:
            self._worker_blocked(key)
        elif op == P.RETURN_LEASED:
            self._on_return_leased(key, payload)
        elif op == P.NOTIFY_UNBLOCKED:
            self._worker_unblocked(key)
        elif op == P.SET_LOG_LABEL:
            wid = self._conn_worker.get(key)
            w = self._workers.get(wid) if wid is not None else None
            if w is not None:
                w.log_label = str(payload)[:64]
        elif op == P.PROFILE_EVENT:
            ev_kind, ev_payload = payload
            if ev_kind == "spans":
                try:
                    self.gcs.record_spans(ev_payload)
                except Exception:   # noqa: BLE001 — tracing is best-effort
                    pass
            elif ev_kind == "metrics":
                try:
                    self.gcs.record_metrics(ev_payload)
                except Exception:   # noqa: BLE001 — telemetry best-effort
                    pass
            elif ev_kind == "coll_reform":
                # a rank process (worker/driver) reformed its collective
                # group; it has no EventLogger of its own, so the
                # literal emit lives here
                try:
                    fields = {k: v for k, v in dict(ev_payload).items()
                              if k != "message"}
                    self.events.warning(
                        "COLLECTIVE_REFORM",
                        str(ev_payload.get("message",
                                           "collective group reformed")),
                        **fields)
                except Exception:   # noqa: BLE001 — accounting only
                    pass
            elif ev_kind == "debug_bundle":
                # a driver/worker captured a post-mortem bundle; it has
                # no EventLogger, so the literal emit lives here
                try:
                    rec = dict(ev_payload)
                    msg = str(rec.pop("message", "debug bundle captured"))
                    self.events.info("DEBUG_BUNDLE", msg, **rec)
                except Exception:   # noqa: BLE001 — accounting only
                    pass
            elif ev_kind == "serve_request":
                # a serve replica promoted a slow/failed request; the
                # replica worker has no EventLogger, so the literal
                # emit lives here (labels stay statically lintable)
                try:
                    rec = dict(ev_payload)
                    req_kind = rec.pop("kind", "slow")
                    msg = str(rec.pop("message", "serve request"))
                    if req_kind == "error":
                        self.events.warning("REQUEST_ERROR", msg, **rec)
                    else:
                        self.events.warning("SLOW_REQUEST", msg, **rec)
                except Exception:   # noqa: BLE001 — accounting only
                    pass
        elif op == P.GET_OBJECTS:
            self._get_objects(key, *payload)
        elif op == P.GET_OBJECTS_FETCH:
            self._get_objects(key, *payload, fetch=True)
        elif op == P.WAIT_OBJECTS:
            self._wait_objects(key, *payload)
        elif op == P.FREE_OBJECTS:
            for oid in payload:
                self.gcs.drop_location(oid)
            self.store.free(payload)
        elif op == P.TASK_DONE:
            self._task_done(key, *payload)
        elif op == P.GEN_ITEM:
            self._gen_item(*payload)
        elif op == P.GEN_NEXT:
            self._gen_next(key, *payload)
        elif op == P.GEN_CLOSE:
            self._gen_close(payload[0])
        elif op == P.KILL_ACTOR:
            self._kill_actor(*payload)
        elif op == P.CANCEL_TASK:
            self._cancel_task(*payload)
        elif op == P.GET_NAMED_ACTOR:
            req_id, name, namespace = payload
            rec = self.gcs.lookup_named_actor(name, namespace)
            info = None
            if rec is not None and rec.state != ACTOR_DEAD:
                info = {"actor_id": rec.spec.actor_id,
                        "name": rec.spec.name,
                        "is_async": rec.spec.is_async,
                        "max_concurrency": rec.spec.max_concurrency}
            self._reply(key, P.NAMED_ACTOR_REPLY, (req_id, info))
        elif op == P.KV_PUT:
            k, v, overwrite = payload
            self.gcs.kv_put(k, v, overwrite)
        elif op == P.KV_GET:
            req_id, k = payload
            self._reply(key, P.KV_REPLY, (req_id, self.gcs.kv_get(k)))
        elif op == P.KV_DEL:
            self.gcs.kv_del(payload)
        elif op == P.KV_KEYS:
            req_id, prefix = payload
            self._reply(key, P.KV_REPLY, (req_id, self.gcs.kv_keys(prefix)))
        elif op == P.FETCH_FUNCTION:
            req_id, function_id = payload
            blob = self.gcs.kv_get(b"fn:" + function_id)
            self._reply(key, P.FUNCTION_REPLY, (req_id, blob))
        elif op == P.CLUSTER_INFO:
            req_id, what = payload
            self._reply(key, P.INFO_REPLY, (req_id, self._cluster_info(what)))
        elif op == P.CREATE_PG:
            self._create_pg(key, payload)
        elif op == P.REMOVE_PG:
            self._remove_pg(payload)
        elif op == P.ACTOR_EXIT:
            actor_id, reason = payload
            self._local_kill_actor(actor_id, True, reason=reason or "exit_actor")
        elif op == P.ACTOR_CHECKPOINT:
            req_id, actor_id, seq, blob = payload
            try:
                # the plane's monotonic seq-guard verdict goes BACK to
                # the worker: a rejected (stale) save must not read as
                # durable there
                ok = self.gcs.save_actor_checkpoint(actor_id, int(seq),
                                                    bytes(blob))
            except Exception as e:  # noqa: BLE001 — the worker blocks
                self._reply(key, P.ERROR_REPLY, (req_id, to_bytes(e)))
            else:
                self._reply(key, P.INFO_REPLY, (req_id, ok))
        elif op == P.ACTOR_CHECKPOINT_GET:
            req_id, actor_id = payload
            try:
                ckpt = self.gcs.get_actor_checkpoint(actor_id)
            except Exception:   # noqa: BLE001 — a miss restores nothing
                ckpt = None
            self._reply(key, P.INFO_REPLY, (req_id, ckpt))
        elif op == P.STATE_QUERY:
            req_id, what, filters = payload
            self._reply(key, P.INFO_REPLY,
                        (req_id, self._state_query(what, filters)))
        elif op == P.REF_REGISTER:
            self._apply_ref_edge(key, op, payload)
        elif op == P.REF_DROP:
            self._apply_ref_edge(key, op, payload)
        elif op == P.REF_BATCH:
            for edge_op, oid in payload:
                self._apply_ref_edge(key, edge_op, oid)
        elif op == P.RETURN_REFS:
            holder_oid, contained = payload
            try:
                self.gcs.pin_contained(holder_oid, contained)
            except Exception:   # noqa: BLE001 — best-effort, like edges
                pass
        elif op == P.OBJ_PROVENANCE:
            try:
                self.gcs.record_provenance(payload)
            except Exception:   # noqa: BLE001 — attribution is best-effort
                pass

    def _reply(self, conn_key: int, op: int, payload: Any) -> None:
        conn = self._conns.get(conn_key)
        if conn is None:
            return
        try:
            conn.send((op, payload))
        except OSError:
            pass

    # ----------------------------------------------------------- submission
    def _debit_route(self, target: NodeID, resources: Dict[str, float]) -> None:
        """Remember resources just routed to a peer so the next routing
        decision doesn't see them as still free (gossiped availability
        lags by up to a heartbeat). Each debit records the peer's
        resource VERSION at routing time: a later snapshot (version
        advanced) already reflects the routed task — as lowered
        availability or as a gossiped pending shape — so the debit
        expires on version advance, not only on the wall-clock TTL. A
        fixed TTL alone double-counted: a burst arriving ~1-2s after a
        previous one saw the peers' fresh free view MINUS the previous
        burst's still-live debits and herded everything onto the local
        node (ISSUE 15, the burst-balance root cause)."""
        if resources:
            self._route_debits.setdefault(target, []).append(
                (time.monotonic(), resources,
                 self._node_versions.get(target)))

    def _candidates(self):
        out = []
        now = time.monotonic()
        ttl = CONFIG.scheduler_route_debit_ttl_s
        seen = set()
        for info in self.gcs.alive_nodes():
            seen.add(info.node_id)
            svc = info.service
            if svc is not None:
                if svc.dead:
                    continue
                # same-process node: availability is exact up to its
                # QUEUE — available_snapshot only reflects dispatched
                # tasks, so during a deferred-dispatch SUBMIT_BATCH the
                # whole burst read "2 CPUs free" here and herded onto
                # this node, leaving spillback to clean up a wave later
                # (the burst-balance flake's root cause, ISSUE 15).
                # Queued-but-undispatched demand is capacity already
                # spoken for: subtract it like a debit that self-clears
                # the instant the task dispatches.
                avail = svc.available_snapshot()
                for shape in svc.pending_demand():
                    for k, v in shape.items():
                        avail[k] = avail.get(k, 0.0) - v
                # a task routed to an in-process peer is visible in
                # NEITHER its snapshot NOR its pending queue until its
                # dispatcher drains the post_remote event — a burst
                # routed within that window dogpiled the first free
                # peer (ISSUE 15). Subtract only YOUNG debits: once the
                # task lands in the peer's pending view (~ms) the
                # pending subtraction above takes over, and a long TTL
                # here would double-count it
                for ts, res, _ver in self._prune_debits(info.node_id,
                                                        now, ttl):
                    if now - ts < min(ttl, 0.25):
                        for k, v in res.items():
                            avail[k] = avail.get(k, 0.0) - v
            else:
                # remote process: availability from heartbeat gossip
                # (RaySyncer-equivalent); subtract what we routed there
                # within the debit ttl so a burst doesn't herd onto one
                # node through the stale view, plus the node's own
                # gossiped queued demand (capacity spoken for by tasks
                # other drivers routed there)
                avail = dict(info.resources_available
                             or info.resources_total)
                for shape in info.pending_shapes or ():
                    for k, v in shape.items():
                        avail[k] = avail.get(k, 0.0) - v
                for _ts, res, _ver in self._prune_debits(
                        info.node_id, now, ttl,
                        current_version=info.resource_version):
                    for k, v in res.items():
                        avail[k] = avail.get(k, 0.0) - v
            self._node_versions[info.node_id] = info.resource_version
            out.append((info.node_id, dict(info.resources_total), avail))
        # nodes that left the cluster take their debit history with them
        for nid in list(self._route_debits):
            if nid not in seen:
                del self._route_debits[nid]
        return out

    def _prune_debits(self, nid: NodeID, now: float, ttl: float,
                      current_version: Optional[int] = None) -> list:
        """Drop expired debits: past the wall-clock TTL, or (remote
        gossip) superseded by a snapshot newer than the one the debit
        was taken against."""
        debits = self._route_debits.get(nid)
        if not debits:
            return []
        live = [(ts, res, ver) for ts, res, ver in debits
                if now - ts < ttl
                and (current_version is None or ver is None
                     or current_version <= ver
                     # a version bump within the submit's own flight
                     # window may predate the task's arrival at the
                     # peer — only trust version expiry once the debit
                     # is old enough for the task to have landed
                     or now - ts < 0.25)]
        if live:
            self._route_debits[nid] = live
        else:
            del self._route_debits[nid]
        return live

    def _peer(self, node_id: NodeID):
        """Handle to a node: self, an in-process NodeService, or a
        _RemotePeer over TCP. None if the node is dead/unreachable."""
        if node_id == self.node_id:
            return self
        info = self.gcs.get_node(node_id)
        if info is None or not info.alive:
            return None
        if info.service is not None:
            return None if info.service.dead else info.service
        rp = self._peers.get(node_id)
        if rp is None or rp.closed:
            try:
                rp = _RemotePeer(self, info)
            except OSError:
                return None
            self._peers[node_id] = rp
        return rp

    def _peer_store(self, node_id: NodeID):
        """The object-plane surface of a peer (get_meta / pin_and_get /
        unpin): the in-process store, or the _RemotePeer itself."""
        peer = self._peer(node_id)
        if peer is None:
            return None
        return peer.store if isinstance(peer, NodeService) else peer

    @staticmethod
    def _arg_refs(spec: P.TaskSpec) -> List[ObjectID]:
        return [val for slot, val in
                list(spec.args) + list(spec.kwargs.values()) if slot == "r"]

    def _pin_submission(self, task_id: TaskID, arg_refs: List[ObjectID],
                        spec: Optional[P.TaskSpec] = None) -> None:
        """Submitted-task references + lineage recording at submission
        (reference: reference_count.h submitted-task refs;
        task lineage, ``task_manager.h:369``). Pins carry this node as
        owner so the control plane can release them if we die."""
        try:
            if arg_refs:
                self.gcs.pin_task_args(task_id, arg_refs,
                                       owner_node=self.node_id)
            if spec is not None and spec.function_id:
                self.gcs.record_lineage(spec)
        except Exception:
            pass

    def _submit_task(self, spec: P.TaskSpec) -> None:
        telemetry.counter_inc(telemetry.M_TASKS_SUBMITTED, 1.0, self._mtags)
        self._owned[spec.task_id] = _OwnedTask(
            spec=spec, kind="task", retries_left=spec.max_retries)
        self._pin_submission(spec.task_id, self._arg_refs(spec), spec)
        self._route_task(spec)

    def _route_task(self, spec: P.TaskSpec,
                    exclude: Optional[Set[NodeID]] = None) -> None:
        strategy = spec.scheduling_strategy
        if isinstance(strategy, sched.PlacementGroupSchedulingStrategy):
            target = self._pg_target_node(strategy)
        else:
            cands = self._candidates()
            if exclude:
                filtered = [c for c in cands if c[0] not in exclude]
                cands = filtered or cands
            target = sched.pick_node(spec.resources, strategy or sched.DEFAULT,
                                     cands, self.node_id, self._rng)
            if _PIPE_DEBUG:
                _pdbg(f"route {spec.task_id.hex()[:8]} "
                      f"{spec.resources} -> "
                      f"{target.hex()[:6] if target else None} cands="
                      + " ".join(f"{nid.hex()[:6]}:{av}"
                                 for nid, _tot, av in cands))
        owned = self._owned.get(spec.task_id)
        if target is None:
            if self._park_infeasible("task", spec):
                # visible to the state API and the stall detector, which
                # diagnoses the unsatisfiable-shape cause from the
                # resources carried in the event
                self._record_event(spec, "PENDING_NODE_ASSIGNMENT")
            else:
                self._fail_returns(spec, RuntimeError(
                    f"no feasible node for resources {spec.resources}"))
            return
        if owned:
            owned.assigned_node = target
            self._record_task_origin(spec.task_id, target)
        # a starved target spills the task back here for re-routing
        spec.origin_node_id = self.node_id.binary()
        if target == self.node_id:
            self._queue_local(spec, "task")
        else:
            peer = self._peer(target)
            if peer is None:
                self._fail_returns(spec, exceptions.WorkerCrashedError(
                    "target node died before dispatch"))
                return
            self._debit_route(target, spec.resources)
            peer.post_remote(("remote_task", spec))

    def _pg_target_node(self, strategy) -> Optional[NodeID]:
        pg = self.gcs.get_pg(strategy.pg_id())
        if pg is None:
            return None
        if pg.get("state") == PG_LOST:
            # journal-restored record: its assignment names nodes that
            # died with the previous head
            return None
        idx = strategy.placement_group_bundle_index
        assignment = pg["assignment"]
        if idx is None or idx < 0:
            idx = 0
        if idx >= len(assignment):
            return None
        return assignment[idx]

    # concurrency: dispatcher-only
    def _queue_local(self, spec: P.TaskSpec, kind: str,
                     actor_spec: Optional[P.ActorSpec] = None) -> None:
        rec = _TaskRecord(spec=spec, kind=kind, actor_spec=actor_spec,
                          retries_left=spec.max_retries,
                          oom_retries_left=CONFIG.task_oom_retries_default)
        if spec.num_returns == -1:
            # the stream will produce HERE: a local record from the
            # start means even pre-first-item end-probes skip the head
            # (same-socket order puts this before any consumer GEN_NEXT)
            self._gen_local.setdefault(
                spec.task_id, {"produced": 0, "done": False,
                               "count": None, "error": None})
        strategy = spec.scheduling_strategy
        if isinstance(strategy, sched.PlacementGroupSchedulingStrategy):
            rec.pg_key = (strategy.pg_id(),
                          max(strategy.placement_group_bundle_index, 0))
        # resolve dependencies first so the event carries the unmet ones
        # (the stall detector diagnoses "blocked on a never-ready
        # object" from exactly this field)
        for slot, val in list(spec.args) + list(spec.kwargs.values()):
            if slot == "r":
                self._add_dep(rec, val)
        self._record_event(spec, "PENDING_ARGS_AVAIL",
                           pending_args=(list(rec.remaining_deps) or None))
        if rec.remaining_deps:
            self._waiting_deps[spec.task_id] = rec
        else:
            self._pending.append(rec)
            if not self._in_batch:
                self._dispatch()

    def _add_dep(self, rec: _TaskRecord, oid: ObjectID) -> None:
        meta = self._lookup_object(oid)
        if meta is not None:
            rec.deps[oid] = meta
        else:
            rec.remaining_deps.add(oid)
            self._dep_index.setdefault(oid, set()).add(rec.spec.task_id)
            self._maybe_reconstruct(oid)

    def _pin_deps(self, rec: "_TaskRecord") -> None:
        """Pin every dependency at its *owning* store just before dispatch,
        refreshing the meta so the worker never reads a segment the owner
        spilled between dep resolution and execution (reference analogue:
        raylet ``PinObjectIDs``, ``node_manager.proto:388``)."""
        for oid in list(rec.deps):
            store = self._owning_store(oid)
            if store is None:
                continue
            fresh = store.pin_and_get(oid)
            if fresh is not None:
                rec.deps[oid] = fresh
                rec.pinned_stores[oid] = store

    def _unpin_deps(self, rec: "_TaskRecord") -> None:
        # Unpin exactly the stores pinned at dispatch — the directory may
        # have changed (e.g. free()) while the task ran.
        for oid, store in rec.pinned_stores.items():
            store.unpin(oid)
        rec.pinned_stores = {}

    def _owning_store(self, oid: ObjectID):
        """The object-plane handle holding the primary copy: our store,
        the owning node's store (in-process cluster), or a _RemotePeer
        (network plane)."""
        if self.store.contains(oid):
            return self.store
        loc = self.gcs.lookup_location(oid)
        if loc is None:
            return None
        return self._peer_store(loc[0])

    # ------------------------------------------ refcount + reconstruction
    def _holder_id(self, conn_key: int) -> tuple:
        return (self.node_id.binary(), conn_key)

    def _apply_ref_edge(self, key: int, op: int, oid: ObjectID) -> None:
        refs = self._conn_refs.setdefault(key, set())
        try:
            if op == P.REF_REGISTER:
                if oid not in refs:
                    refs.add(oid)
                    self.gcs.ref_register(oid, self._holder_id(key))
            elif oid in refs:
                refs.discard(oid)
                self.gcs.ref_drop(oid, self._holder_id(key))
        except Exception:
            pass

    def _on_ref_zero(self, payload) -> None:
        self._events.put(("ref_zero", payload["object_id"],
                          payload["node_id"]))

    def _local_ref_zero(self, oid: ObjectID,
                        owner_node: Optional[NodeID]) -> None:
        """No process holds a reference and no task uses the object:
        free our copy (primary or pulled secondary). Arena blocks whose
        bytes were ever read go through the free-quarantine."""
        if owner_node == self.node_id:
            self.gcs.drop_location(oid)
        if self.store.contains(oid):
            self.store.free([oid])

    def _maybe_reconstruct(self, oid: ObjectID) -> bool:
        """Lost object with recorded lineage: resubmit its creating task
        (reference: ``object_recovery_manager.h:90``). Returns True if a
        reconstruction is (already) in flight. The control plane's
        claim_lineage is the gate: it hands out the spec only when the
        object was sealed once and is now locationless, to exactly one
        claimant — so in-flight first executions and concurrent
        reconstructions are never duplicated."""
        if oid in self._reconstructing:
            return True
        if self.store.contains(oid):
            return False
        try:
            spec = self.gcs.claim_lineage(oid)
        except Exception:
            return False
        if spec is None:
            return False
        if spec.task_id in self._owned:
            return True         # resubmission already in flight locally
        self._reconstructing.update(spec.return_ids)
        self._owned[spec.task_id] = _OwnedTask(
            spec=spec, kind="task", retries_left=spec.max_retries)
        self._pin_submission(spec.task_id, self._arg_refs(spec))
        # creating-task args may themselves be lost: recurse
        for dep in self._arg_refs(spec):
            if not self._object_exists(dep):
                self._maybe_reconstruct(dep)
        self._route_task(spec)
        return True

    def _object_exists(self, oid: ObjectID) -> bool:
        """Existence probe for wait()/readiness checks: metadata only,
        never pulls a cross-host payload (that happens at read time)."""
        if self.store.contains(oid):
            return True
        tid = TaskID(TaskID.KIND + oid.binary()[:15])
        owned = self._owned.get(tid)
        if owned is not None and not owned.done:
            # our own still-running task: its returns exist nowhere yet
            # — park without a head directory round trip (owner-based
            # resolution; the completion event resolves the waiter)
            return False
        origin = self._task_origin.get(tid)
        if origin is not None and origin != self.node_id:
            remote = self._peer_store(origin)
            if remote is not None and remote is not self.store:
                try:
                    if remote.get_meta(oid) is not None:
                        return True
                except Exception:   # noqa: BLE001 — head fallback below
                    pass
        loc = self.gcs.lookup_location(oid)
        if loc is None:
            return False
        handle = self._peer_store(loc[0])
        if handle is None:
            # owner unreachable; the directory-shared meta is the best
            # evidence (an actual get will pull or fail loudly)
            return loc[1].has_value()
        if isinstance(handle, _RemotePeer):
            return handle.peek(oid) is not None
        return handle.get_meta(oid) is not None

    def _record_task_origin(self, task_id: TaskID, node_id: NodeID
                            ) -> None:
        self._task_origin[task_id] = node_id
        self._task_origin.move_to_end(task_id)
        while len(self._task_origin) > 8192:
            self._task_origin.popitem(last=False)

    def _lookup_object(self, oid: ObjectID) -> Optional[ObjectMeta]:
        meta = self.store.get_meta(oid)
        if meta is not None:
            return meta
        # owner-based resolution first (reference:
        # ownership_based_object_directory.h): we submitted the creating
        # task, so we know which node sealed its returns — read straight
        # from that store, no head directory RTT. Miss (freed, moved,
        # reconstructed elsewhere) falls back to the head.
        origin = self._task_origin.get(
            TaskID(TaskID.KIND + oid.binary()[:15]))
        if origin is not None and origin != self.node_id:
            remote = self._peer_store(origin)
            if remote is not None and remote is not self.store:
                try:
                    meta = remote.get_meta(oid)
                except Exception:   # noqa: BLE001 — peer gone; head
                    meta = None     # fallback resolves or fails cleanly
                if meta is not None:
                    return meta
        loc = self.gcs.lookup_location(oid)
        if loc is None:
            return None
        nid, meta = loc
        remote = self._peer_store(nid)
        if remote is not None and remote is not self.store:
            # Always route cross-node reads through the owning store:
            # get_meta marks the entry read (ever_read) and restores
            # spilled entries, so the owner will never spill-and-free an
            # arena block whose bytes a remote reader's zero-copy views
            # still alias. Returning the directory-shared meta directly
            # bypassed that tracking (silent corruption under memory
            # pressure). Reference analogue: reads go through the primary
            # raylet's plasma store / RestoreSpilledObjects
            # (``local_object_manager.h:110``).
            return remote.get_meta(oid)
        if (meta.shm_name is None and meta.inline is None
                and meta.error is None and meta.arena_ref is None):
            return None
        return meta

    # ------------------------------------------------------------- dispatch
    # concurrency: dispatcher-only
    def _dispatch(self) -> None:
        """Scan the local queue, dispatching every task whose resources and
        worker are available (reference:
        ``LocalTaskManager::DispatchScheduledTasksToWorkers``,
        ``local_task_manager.cc:105``)."""
        if not self._pending:
            return
        failed_envs: Set[str] = set()
        starved_envs: Set[str] = set()
        for shape in self._pending.shapes():
            env_key = shape[2]
            bucket = self._pending.bucket(shape)
            exhausted = False
            while bucket:
                rec = bucket[0]
                if rec.cancelled:
                    self._pending.popleft(shape)
                    continue
                if not self._try_acquire(rec):
                    exhausted = True
                    break                # this shape doesn't fit right now
                if env_key in starved_envs:
                    # spawn already requested this pass for this env;
                    # don't rescan the idle deque per bucket
                    self._release_charge(rec)
                    self._maybe_spawn_worker(rec)
                    break
                wid = self._acquire_worker(env_key)
                if wid is None:
                    self._release_charge(rec)
                    if (self._env_spawn_failures.get(env_key, 0)
                            >= CONFIG.worker_startup_max_failures):
                        failed_envs.add(env_key)
                        # workers for this env die on startup repeatedly —
                        # fail fast instead of pending forever (reference:
                        # PopWorker status callback, ``worker_pool.h:152``)
                        self._pending.popleft(shape)
                        self._fail_pending_rec(
                            rec, exceptions.RuntimeEnvSetupError(
                                f"workers for task {rec.spec.name!r} "
                                f"failed to start "
                                f"{CONFIG.worker_startup_max_failures} "
                                "times; last worker log tail:\n"
                                + self._env_spawn_error.get(
                                    env_key, "<no log>")))
                        continue
                    starved_envs.add(env_key)
                    # parallel cold-start ramp: request a spawn per
                    # starved task up to the startup-concurrency cap —
                    # one spawn per dispatch pass would serialize a
                    # burst's ramp-up behind single worker cold-starts
                    for _ in range(min(len(bucket),
                                       CONFIG.maximum_startup_concurrency)):
                        self._maybe_spawn_worker(rec)
                    # a different-env shape behind this one may still
                    # have an idle worker; move to the next bucket
                    break
                self._pending.popleft(shape)
                self._assign(rec, wid)
            if bucket and (exhausted or self._num_starting == 0):
                # lease extra tasks onto busy workers only when no new
                # worker is coming: capacity is the binding constraint
                # (exhausted), or the pool/startup cap blocked spawning
                # (nothing STARTING even after the spawn attempts above
                # — the num_cpus=0 burst regime). When workers are
                # merely cold-starting, DON'T pipe: it would park a
                # task behind a possibly-long running one (head-of-line
                # blocking) when a spawning worker could serve it in
                # milliseconds.
                self._pipeline_into_busy(shape, bucket)
            self._pending.drop_empty(shape)
        # fresh budget for future submissions: the blacklist applies to
        # tasks pending in this pass, not to the env forever
        for env in failed_envs:
            self._env_spawn_failures.pop(env, None)

    def _pipeline_into_busy(self, shape: tuple, bucket: deque) -> None:
        """Lease extra same-shape tasks onto workers already running that
        shape, up to a small depth (reference: worker-lease reuse — the
        owner keeps pushing tasks to a leased worker instead of paying a
        scheduler round trip per task, ``direct_task_transport.h``).
        Piped tasks hold NO resource charge: the worker executes
        serially, so only its running task consumes resources; the
        charge transfers on each completion (identical shape). Excluded:
        placement groups (per-bundle pools) and TPU tasks (exclusive
        accelerator slot ids differ per task)."""
        depth = CONFIG.worker_pipeline_depth
        pg_key, res, _env = shape
        if (depth <= 1 or pg_key is not None
                or any(r == "TPU" for r, _ in res)):
            return
        if len(bucket) < 2:
            # the lease-reuse win only pays on task streams; see the
            # matching len(bucket) > 1 condition in the drain loop
            return
        for w in self._workers.values():
            if not bucket:
                break
            if (w.state != "BUSY" or w.conn is None or w.task is None
                    or w.task.kind != "task"
                    or w.task.blocked_depth > 0 or w.blocked_gets
                    or getattr(w.task, "_pending_shape", None) != shape):
                # never lease behind a task blocked in get(): the queue
                # would park until it unblocks (and could BE what it
                # waits on)
                continue
            # drain down to ONE remaining task, never to zero: a piped
            # task leaves _pending — invisible to _spill_starved_pending
            # — so the bucket's last task always stays schedulable/
            # spillback-rescuable instead of starving head-of-line
            # behind a long occupant while another node idles (the
            # ISSUE 15 burst-audit regression, closed for every bucket
            # size, not just lone tasks)
            while len(bucket) > 1 and len(w.pipeline) + 1 < depth:
                rec = bucket[0]
                if rec.no_pipe or rec.kind != "task":
                    # bounced-once tasks and actor creations (which
                    # share a shape bucket with plain tasks) wait for a
                    # normal assignment
                    break
                self._pending.popleft(shape)
                if rec.cancelled:
                    continue
                rec.worker_id = w.worker_id
                self._running[rec.spec.task_id] = rec
                self._record_event(rec.spec, "RUNNING")
                self._pin_deps(rec)
                rec.spec.accel_ids = None
                w.lease_seq += 1
                rec.lease_seq = w.lease_seq
                w.pipeline.append(rec)
                _pdbg(f"pipe {rec.spec.task_id.hex()[:8]} -> "
                      f"{w.worker_id.hex()[:6]} seq={rec.lease_seq}")
                self._send_execute(w, (rec.kind, rec.spec, rec.deps,
                                       rec.actor_spec, rec.lease_seq))

    def _spill_starved_pending(self) -> None:
        """Re-route queued tasks that have starved locally while another
        node has free capacity (reference: lease spillback,
        ``cluster_task_manager.cc`` — a lease that can't be served locally
        is redirected rather than parked forever). Without this, a stale
        routing view can strand a task behind a long-running occupant
        while the rest of the cluster idles."""
        delay = CONFIG.scheduler_spillback_delay_s
        if delay <= 0 or not self._pending:
            return
        now = time.monotonic()
        cands = None
        spilled = 0
        for shape in self._pending.shapes():
            if spilled >= 10:      # bound per-tick dispatcher work
                break
            bucket = self._pending.bucket(shape)
            if not bucket:
                continue
            rec = bucket[0]
            if (rec.cancelled or rec.pg_key is not None
                    or rec.kind != "task"
                    or now - rec.queued_at < delay):
                continue
            strategy = rec.spec.scheduling_strategy
            if (isinstance(strategy, sched.NodeAffinitySchedulingStrategy)
                    and not strategy.soft):
                continue
            if self._try_acquire(rec):
                # fits locally after all — dispatch will pick it up
                self._release_charge(rec)
                continue
            if cands is None:
                cands = self._candidates()
            fit_now = [(nid, total, avail) for nid, total, avail in cands
                       if nid != self.node_id
                       and sched.fits(avail, rec.spec.resources)]
            if not fit_now:
                continue
            self._pending.remove(rec)
            spilled += 1
            origin = (NodeID(rec.spec.origin_node_id)
                      if rec.spec.origin_node_id else self.node_id)
            if origin == self.node_id:
                # we own the routing decision: re-route, away from here
                self._route_task(rec.spec, exclude={self.node_id})
            else:
                peer = self._peer(origin)
                if peer is None:
                    # origin died; node-death handling owns the retry —
                    # put the task back rather than dropping it
                    self._pending.append(rec)
                    spilled -= 1
                    continue
                peer.post_remote(("spillback_task", rec.spec, self.node_id))

    def _on_spillback_task(self, spec: P.TaskSpec,
                           starved_node: NodeID) -> None:
        """Owner-side: a target couldn't serve a task we routed to it and
        capacity exists elsewhere — route it again, avoiding the starved
        node."""
        owned = self._owned.get(spec.task_id)
        if owned is None or owned.done:
            return                       # completed or cancelled meanwhile
        if owned.assigned_node != starved_node:
            return                       # stale spillback (already moved)
        self._route_task(spec, exclude={starved_node})

    def _fail_pending_rec(self, rec: _TaskRecord, exc: Exception) -> None:
        """Fail a queued (never-dispatched) task record."""
        self._unpin_deps(rec)
        self._record_event(rec.spec, "FAILED")
        # seal the creation/return refs with the root-cause error first;
        # _handle_actor_death below then sees them sealed and won't
        # overwrite with a generic ActorDiedError
        self._fail_returns(rec.spec, exc)
        if rec.kind == "actor_create" and rec.actor_spec is not None:
            aid = rec.actor_spec.actor_id
            st = self._actors.get(aid)
            if st is not None:
                # a restart would hit the same broken env; full death path
                # also drains queued method calls (they'd hang otherwise)
                st["no_restart"] = True
                self._handle_actor_death(aid, str(exc))
            else:
                self.gcs.set_actor_state(aid, ACTOR_DEAD, reason=str(exc))

    def _try_acquire(self, rec: _TaskRecord) -> bool:
        demand = rec.spec.resources
        with self._res_lock:
            if rec.pg_key is not None:
                pool = self.pg_reservations.get(rec.pg_key)
                if pool is None or not sched.fits(pool, demand):
                    return False
                sched.subtract(pool, demand)
            else:
                if not sched.fits(self.resources_available, demand):
                    return False
                sched.subtract(self.resources_available, demand)
            n_tpu = int(demand.get("TPU", 0))
            if n_tpu >= 1 and len(self._tpu_free) >= n_tpu:
                rec.accel_ids = [self._tpu_free.popleft()
                                 for _ in range(n_tpu)]
        rec.charge = dict(demand)
        return True

    def _release_charge(self, rec: _TaskRecord) -> None:
        if rec.charge is None:
            return
        charge = dict(rec.charge)
        if rec.blocked_depth > 0:
            # the CPU portion was already returned when the worker
            # blocked in get(); releasing it again would mint capacity
            charge.pop("CPU", None)
            rec.blocked_depth = 0
        with self._res_lock:
            pool = self._rec_charge_pool(rec)
            if pool is not None:
                sched.add(pool, charge)
            rec.accel_ids = self._return_tpu_slots(rec.accel_ids)
        rec.charge = None

    # concurrency: requires(node.res)
    def _return_tpu_slots(self, ids) -> None:
        """Return exclusive slot ids to the pool (callers hold
        ``_res_lock``); returns None for assign-back convenience."""
        if ids:
            self._tpu_free.extend(ids)
        return None

    def _rec_charge_pool(self, rec: _TaskRecord):
        if rec.pg_key is not None:
            return self.pg_reservations.get(rec.pg_key)
        return self.resources_available

    def _worker_blocked(self, conn_key: int) -> None:
        """A worker entered a blocking get(): return its CPU so the
        tasks it waits on can be scheduled here — otherwise nested
        submission deadlocks once parents hold every CPU (reference:
        ``NotifyDirectCallTaskBlocked``)."""
        wid = self._conn_worker.get(conn_key)
        w = self._workers.get(wid) if wid is not None else None
        if w is None:
            return
        w.blocked_gets += 1
        rec = w.task
        cpu = rec.charge.get("CPU", 0.0) if (
            rec is not None and rec.charge is not None) else 0.0
        if not cpu:
            # no CPU to return (actor method: the creation holds the
            # charge) — but the pool-cap exemption just changed, and a
            # parked actor creation may now have room to spawn into
            if w.blocked_gets == 1 and not self._in_batch:
                self._dispatch()
            return
        rec.blocked_depth += 1
        if rec.blocked_depth > 1:
            return                  # CPU already returned
        with self._res_lock:
            pool = self._rec_charge_pool(rec)
            if pool is not None:
                sched.add(pool, {"CPU": cpu})
        if not self._in_batch:
            self._dispatch()

    def _on_return_leased(self, conn_key: int, entries: list) -> None:
        """A worker entering a blocking get() handed back its unstarted
        leased tasks (they could be the very children it waits on —
        nested submission would deadlock behind it). The WORKER drained
        its own queue, so it will never run these; requeueing them here
        is double-execution-free by construction.

        Sequenced handshake: each entry is ``(task_id, lease_seq)``
        echoing the seq the grant's EXECUTE carried. A return is
        honored only when the seq matches the task's CURRENT grant on
        THIS worker — a rescue delayed past a re-grant (the task was
        already requeued and dispatched again, here or elsewhere) names
        a superseded seq and is dropped instead of un-assigning the
        live incarnation (the double-dispatch/strand race that kept
        pipelining default-off)."""
        wid = self._conn_worker.get(conn_key)
        w = self._workers.get(wid) if wid is not None else None
        if w is None:
            return
        by_id = {r.spec.task_id: r for r in w.pipeline}
        for tid, seq in entries:
            rec = by_id.get(tid)
            _pdbg(f"return_leased {tid.hex()[:8]} seq={seq} from "
                  f"{w.worker_id.hex()[:6]} found={rec is not None}")
            if rec is not None and rec.lease_seq == seq:
                w.pipeline.remove(rec)
                self._running.pop(tid, None)
                self._unpin_deps(rec)
                rec.worker_id = None
                rec.no_pipe = True
                self._pending.append(rec)
                continue
            if rec is None:
                # handoff raced the bounce: a completion already
                # promoted this lease to w.task (charge and all) while
                # the worker was handing it back — un-assign it here or
                # it stays "running" forever on a worker that never
                # queued it. Only for the SAME grant: a seq mismatch
                # means w.task is a newer grant the worker did accept.
                cur = w.task
                if (cur is not None and cur.spec.task_id == tid
                        and cur.lease_seq == seq):
                    self._running.pop(tid, None)
                    self._unpin_deps(cur)
                    self._release_charge(cur)
                    cur.worker_id = None
                    cur.no_pipe = True
                    if w.state == "BUSY":
                        self._mark_idle(w)
                    self._pending.append(cur)
                    continue
            _pdbg(f"stale rescue dropped {tid.hex()[:8]} seq={seq}")
        if not self._in_batch:
            self._dispatch()

    def _worker_unblocked(self, conn_key: int) -> None:
        wid = self._conn_worker.get(conn_key)
        w = self._workers.get(wid) if wid is not None else None
        if w is None:
            return
        if w.blocked_gets > 0:
            w.blocked_gets -= 1
        rec = w.task
        if rec is None or rec.charge is None or rec.blocked_depth == 0:
            # an idle-but-was-blocked worker became leasable again:
            # pending tasks skipped it while _acquire_worker held it out
            if (w.state == "IDLE" and not w.blocked_gets
                    and self._pending and not self._in_batch):
                self._dispatch()
            return
        rec.blocked_depth -= 1
        if rec.blocked_depth > 0:
            return                  # other threads still blocked
        cpu = rec.charge.get("CPU", 0.0)
        with self._res_lock:
            pool = self._rec_charge_pool(rec)
            if pool is not None:
                # may drive availability transiently negative: the
                # resumed task runs NOW regardless, and new dispatch
                # just waits for real capacity (same oversubscription
                # the reference accepts on unblock)
                sched.subtract(pool, {"CPU": cpu})
        # the pipeliner skipped this worker while blocked_gets > 0;
        # now that it is leasable again, pending same-shape tasks can
        # pipe onto it without waiting for the next completion/tick
        if not w.blocked_gets and self._pending and not self._in_batch:
            self._dispatch()

    def _rec_env_key(self, rec: "_TaskRecord") -> str:
        from . import runtime_env as renv
        spec_env = (rec.actor_spec.runtime_env
                    if rec.actor_spec is not None
                    else rec.spec.runtime_env)
        return renv.env_key(spec_env)

    def _rec_runtime_env(self, rec: "_TaskRecord") -> Optional[dict]:
        return (rec.actor_spec.runtime_env if rec.actor_spec is not None
                else rec.spec.runtime_env)

    def _acquire_worker(self, env_key: str = "") -> Optional[WorkerID]:
        """Pop an idle worker whose runtime env matches (pool keyed by
        env, reference: ``WorkerPool::PopWorker``)."""
        kept = []
        found = None
        while self._idle:
            wid = self._idle.popleft()
            w = self._workers.get(wid)
            if w is None or w.state != "IDLE":
                continue
            if w.blocked_gets:
                # a thread of this worker is still parked in a blocking
                # get(): a grant would only bounce straight back
                # (reader-side rescue) and ping-pong until it unblocks —
                # keep it queued, skip it for now
                kept.append(wid)
                continue
            if w.env_key == env_key:
                found = wid
                break
            kept.append(wid)
        self._idle.extendleft(reversed(kept))
        return found

    def _maybe_spawn_worker(self, rec: Optional["_TaskRecord"] = None
                            ) -> None:
        self._reap_startup_failures()
        env_key = self._rec_env_key(rec) if rec is not None else ""
        # workers blocked in a get() don't count against the pool cap:
        # deep nested submission (recursion) parks a worker per level,
        # and capping on them deadlocks the leaves that would unblock
        # them (reference: WorkerPool grows past the cap while direct
        # call workers are blocked). blocked_gets covers actors too —
        # their method records hold no CPU charge so blocked_depth
        # never rises, but an actor waiting on a nested actor creation
        # (a collective-group coordinator, say) pins its process just
        # the same
        active = sum(1 for w in self._workers.values()
                     if w.state != "DEAD"
                     and not w.blocked_gets
                     and not (w.task is not None
                              and w.task.blocked_depth > 0))
        if active >= self._max_workers:
            # pool full of other-env workers would starve this env forever;
            # evict one idle mismatched worker to make room (reference:
            # WorkerPool idle eviction, ``worker_pool.h:152``)
            if not self._evict_idle_worker(exclude_env=env_key):
                return
        if self._num_starting >= CONFIG.maximum_startup_concurrency:
            return
        if rec is not None:
            self._spawn_worker(env_key, self._rec_runtime_env(rec))
        else:
            self._spawn_worker()

    def _evict_idle_worker(self, exclude_env: str) -> bool:
        """Kill one idle worker whose env differs from ``exclude_env``."""
        for wid in list(self._idle):
            w = self._workers.get(wid)
            if w is None or w.state != "IDLE" or w.env_key == exclude_env:
                continue
            self._kill_worker(wid)
            return True
        return False

    def _kill_worker(self, wid: WorkerID) -> None:
        w = self._workers.pop(wid, None)
        if w is None:
            return
        try:
            self._idle.remove(wid)
        except ValueError:
            pass
        w.state = "DEAD"
        if w.conn_key is not None:
            self._conn_worker.pop(w.conn_key, None)
        if w.proc is not None:
            try:
                w.proc.kill()
            except OSError:
                pass

    def _reap_idle_workers(self) -> None:
        """Kill workers idle beyond CONFIG.idle_worker_killing_time_s,
        keeping a floor of num_cpus default-env workers warm (reference:
        ``WorkerPool::TryKillingIdleWorkers``)."""
        timeout = CONFIG.idle_worker_killing_time_s
        if timeout <= 0:
            return
        floor = int(self.resources_total.get("CPU", 0))
        now = time.monotonic()
        n_default = sum(
            1 for wid in self._idle
            if (w := self._workers.get(wid)) is not None
            and w.state == "IDLE" and w.env_key == "")
        for wid in list(self._idle):
            w = self._workers.get(wid)
            if (w is None or w.state != "IDLE"
                    or now - w.idle_since < timeout):
                continue
            if w.env_key == "":
                # the warm floor applies to default-env workers only
                if n_default <= floor:
                    continue
                n_default -= 1
            self._kill_worker(wid)

    def _mark_idle(self, w: _Worker) -> None:
        w.state = "IDLE"
        w.task = None
        w.idle_since = time.monotonic()
        self._idle.append(w.worker_id)

    def _worker_log_tail(self, w: _Worker, nbytes: int = 2048) -> str:
        if not w.log_path:
            return "<no log>"
        try:
            with open(w.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - nbytes))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return "<log unreadable>"

    def _reap_startup_failures(self) -> None:
        """Workers that died before registering never produce a conn_closed
        event; reap them here so startup slots aren't leaked forever, and
        count consecutive per-env failures so tasks can fail fast."""
        now = time.monotonic()
        for wid, w in list(self._workers.items()):
            if w.state != "STARTING" or w.proc is None:
                continue
            timeout = (w.register_timeout_s
                       or CONFIG.worker_register_timeout_s)
            if (w.proc.poll() is not None
                    or now - w.started_at > timeout):
                died = w.proc.poll() is not None
                if not died:
                    try:
                        w.proc.kill()
                    except OSError:
                        pass
                del self._workers[wid]
                self._num_starting = max(0, self._num_starting - 1)
                if died or w.env_setup:
                    self.events.error(
                        "WORKER_START_FAILURE",
                        "worker died before registering" if died else
                        "runtime env setup timed out",
                        env_key=w.env_key,
                        pid=w.proc.pid if w.proc else None)
                    # Processes that exited on their own count toward the
                    # env failure budget — a slow registration (killed at
                    # the timeout) is load, not a broken env, and must
                    # not blacklist the default pool. EXCEPT during an
                    # env build: hitting the (much larger) setup deadline
                    # means the build hung; retrying would wipe and
                    # rebuild the venv from zero forever.
                    self._env_spawn_failures[w.env_key] = (
                        self._env_spawn_failures.get(w.env_key, 0) + 1)
                    self._env_spawn_error[w.env_key] = (
                        self._worker_log_tail(w) if died else
                        f"runtime env setup did not finish within "
                        f"{timeout:.0f}s:\n" + self._worker_log_tail(w))

    def _spawn_worker(self, env_key: str = "",
                      worker_runtime_env: Optional[dict] = None
                      ) -> WorkerID:
        from . import runtime_env as renv
        wid = WorkerID.from_random()
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"worker-{wid.hex()[:12]}.log")
        out = open(log_path, "ab")
        env = dict(os.environ)
        env["RTPU_WORKER"] = "1"
        # stdout lands in the worker log file; unbuffered so the log
        # tailer streams prints to the driver as they happen
        env["PYTHONUNBUFFERED"] = "1"
        # Workers never grab the TPU; the driver owns device compute. Also
        # disable TPU-attach hooks in sitecustomize (saves ~2s/spawn).
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        if CONFIG.tracing_enabled:
            # workers read config from env; the driver's _system_config
            # reload doesn't reach their processes
            env["RTPU_TRACING_ENABLED"] = "1"
        cwd = os.getcwd()
        if worker_runtime_env:
            overrides, env_cwd = renv.stage(worker_runtime_env,
                                            self.session_dir)
            env.update(overrides)
            if env_cwd:
                cwd = env_cwd
        # The framework may be importable only via the driver's cwd (not
        # installed); a runtime_env working_dir changes the worker's cwd,
        # so make ray_tpu importable explicitly. Appended last: staged
        # user code shadows it.
        fw_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        pp = env.get("PYTHONPATH", "")
        have = set(pp.split(os.pathsep))
        # Workers resolve by-reference pickles (plain functions/classes
        # passed as args) against the USER-LEVEL import paths of this
        # node's process — locally the driver's script dir, so a
        # function from the user's script module imports inside the
        # worker (reference: same-node workers share the job's
        # environment). Site-packages/stdlib dirs are excluded (they'd
        # shadow a pip runtime-env venv's pinned packages), and a staged
        # working_dir opts out entirely — its snapshot must stay
        # hermetic, not fall through to live driver directories.
        extra = []
        if not (worker_runtime_env
                and "working_dir" in worker_runtime_env):
            extra = [p for p in _user_sys_paths() if p not in have]
        from .config import fw_importable_without_path
        if (not fw_importable_without_path() and fw_root not in have
                and fw_root not in extra):
            extra.append(fw_root)
        if extra:
            env["PYTHONPATH"] = ((pp + os.pathsep if pp else "")
                                 + os.pathsep.join(extra))
        # pip envs go through the bootstrap, which builds/reuses a cached
        # venv in the worker process (never blocking this dispatcher) and
        # execs the real worker under the venv interpreter
        worker_mod = "ray_tpu._private.worker"
        pip = renv.pip_spec(worker_runtime_env)
        if pip is not None:
            worker_mod = "ray_tpu._private.worker_bootstrap"
            env["RTPU_PIP_SPEC"] = json.dumps(pip)
            env["RTPU_ENV_CACHE_DIR"] = os.path.join(
                self.session_dir, "runtime_envs")
            register_timeout = (CONFIG.worker_register_timeout_s
                                + CONFIG.runtime_env_setup_timeout_s)
        else:
            register_timeout = 0.0
        proc = subprocess.Popen(
            [sys.executable, "-m", worker_mod,
             self.socket_path, self.node_id.hex(), wid.hex()],
            stdout=out, stderr=subprocess.STDOUT, env=env,
            cwd=cwd)
        out.close()
        self._workers[wid] = _Worker(worker_id=wid, proc=proc,
                                     env_key=env_key, log_path=log_path,
                                     register_timeout_s=register_timeout,
                                     env_setup=pip is not None)
        self._num_starting += 1
        return wid

    # concurrency: dispatcher-only
    def _assign(self, rec: _TaskRecord, wid: WorkerID) -> None:
        telemetry.counter_inc(telemetry.M_TASKS_DISPATCHED, 1.0, self._mtags)
        telemetry.hist_observe(telemetry.M_QUEUE_WAIT,
                               time.monotonic() - rec.queued_at, self._mtags)
        w = self._workers[wid]
        w.state = "ACTOR" if rec.kind == "actor_create" else "BUSY"
        w.task = rec
        w.assigned_at = time.monotonic()
        rec.worker_id = wid
        if rec.kind == "actor_create":
            w.actor_id = rec.actor_spec.actor_id
            st = self._actors.get(rec.actor_spec.actor_id)
            if st is not None:
                st["worker_id"] = wid
        self._running[rec.spec.task_id] = rec
        self._record_event(rec.spec, "RUNNING")
        self._pin_deps(rec)
        rec.spec.accel_ids = rec.accel_ids
        w.lease_seq += 1
        rec.lease_seq = w.lease_seq
        _pdbg(f"assign {rec.spec.task_id.hex()[:8]} ({rec.kind}) -> "
              f"{w.worker_id.hex()[:6]} seq={rec.lease_seq}")
        self._send_execute(w, (rec.kind, rec.spec, rec.deps,
                               rec.actor_spec, rec.lease_seq))

    # ------------------------------------------------------------ completion
    # concurrency: dispatcher-only
    def _task_done(self, conn_key: int, task_id, metas: List[ObjectMeta],
                   error: Optional[bytes], kind: str,
                   gen_count: Optional[int] = None) -> None:
        rec = self._running.pop(task_id, None)
        _pdbg(f"done {task_id.hex()[:8]} known={rec is not None} "
              f"metas={len(metas)} err={error is not None}")
        if rec is not None:
            self._unpin_deps(rec)
        if gen_count is not None:
            # streaming task finished: record the stream end (count +
            # terminal error) so consumers at any index past the end get
            # StopIteration/the error instead of waiting forever
            lg = self._gen_local.setdefault(
                task_id, {"produced": 0, "done": False, "count": None,
                          "error": None})
            lg.update(done=True, count=gen_count, error=error,
                      produced=max(lg["produced"], gen_count))
            self.gcs.gen_done(task_id, gen_count, error)
            self._gen_consumed_cache.pop(task_id, None)
        for meta in metas:
            self._seal_object(meta)
        if rec is None:
            return
        self._record_event(rec.spec, "FINISHED" if error is None else "FAILED")
        telemetry.counter_inc(
            telemetry.M_TASKS_FINISHED, 1.0,
            self._mtags + (("status", "ok" if error is None else "error"),))
        owned = self._owned.pop(task_id, None)
        if owned is not None:
            # we are the owner: settle inline on the dispatcher instead
            # of a pubsub fan-out + one more queued event per completion
            # (the only subscriber work is this owned-pop + arg unpin)
            try:
                self.gcs.unpin_task_args(task_id)
            except Exception:
                pass
        else:
            # remote owner: its node's subscriber settles it
            self.gcs.publish("TASK_FINISHED", {"task_id": task_id,
                                               "ok": error is None})
        w = self._workers.get(rec.worker_id) if rec.worker_id else None
        if rec.kind == "actor_create":
            self._actor_creation_done(rec, error)
            if not self._in_batch:      # a burst dispatches once, at end
                self._dispatch()
            return
        if rec.kind == "task" and w is not None and w.pipeline:
            # leased pipeline: hand the charge to the next task of the
            # identical shape — the pool totals don't move
            nxt = w.pipeline.popleft()
            _pdbg(f"handoff {w.worker_id.hex()[:6]}: "
                  f"{rec.spec.task_id.hex()[:8]} -> "
                  f"{nxt.spec.task_id.hex()[:8]}")
            telemetry.counter_inc(telemetry.M_LEASE_REUSED, 1.0, self._mtags)
            nxt.charge, rec.charge = rec.charge, None
            w.task = nxt
            w.assigned_at = time.monotonic()
        else:
            self._release_charge(rec)
            if w is not None and w.state == "BUSY":
                self._mark_idle(w)
        if rec.kind == "actor_call" and w is not None:
            w.task = None
        if not self._in_batch:          # a burst dispatches once, at end
            self._dispatch()

    def _seal_object(self, meta: ObjectMeta) -> None:
        self.store.adopt(meta)
        telemetry.counter_inc(telemetry.M_STORE_PUTS, 1.0, self._mtags)
        telemetry.counter_inc(telemetry.M_STORE_PUT_BYTES,
                              float(meta.size), self._mtags)
        self.gcs.publish_location(meta.object_id, self.node_id, meta)
        self.gcs.publish("OBJECT", (meta.object_id, meta))

    # ------------------------------------------------- streaming returns
    def _gen_item(self, task_id, index: int, meta: ObjectMeta) -> None:
        """A streaming task produced item ``index`` (reference:
        ReportGeneratorItemReturns — a worker<->owner report). The item
        is an ordinary object once sealed; the stream counters live in
        a NODE-LOCAL record, and reach the head only for streams whose
        owner sits elsewhere (a traveled ref) — per-item control
        traffic stays off the head on the owner-local hot path
        (VERDICT r04 weak #6)."""
        self._seal_object(meta)
        lg = self._gen_local.setdefault(
            task_id, {"produced": 0, "done": False, "count": None,
                      "error": None})
        lg["produced"] = max(lg["produced"], index + 1)
        if task_id not in self._owned:
            # owner is remote: its node's parked waiters unblock off
            # the head's GEN pubsub
            self.gcs.gen_update(task_id, index + 1)
        consumed = self._gen_consumed_cache.get(task_id)
        if consumed:
            # credit that arrived before the task started here
            self._relay_gen_ack(task_id, consumed)
        self._resolve_gen_waiters(task_id, index, meta)

    def _relay_gen_ack(self, task_id, consumed: int) -> None:
        rec = self._running.get(task_id)
        if rec is not None and rec.worker_id is not None:
            w = self._workers.get(rec.worker_id)
            if w is not None and w.conn is not None:
                try:
                    w.conn.send((P.GEN_ACK, (task_id, consumed)))
                except OSError:
                    pass

    def _resolve_gen_waiters(self, task_id, index: int,
                             meta: ObjectMeta) -> None:
        for conn_key, req_id in self._gen_waiters.pop((task_id, index), ()):
            self._reply(conn_key, P.INFO_REPLY, (req_id, ("item", meta)))
            self._gen_consume(task_id, index + 1)

    def _gen_next(self, conn_key: int, req_id: int, task_id,
                  index: int) -> None:
        oid = ObjectID.for_gen_item(task_id, index)
        meta = self._lookup_object(oid)
        if meta is not None:
            self._reply(conn_key, P.INFO_REPLY, (req_id, ("item", meta)))
            self._gen_consume(task_id, index + 1)
            return
        # producer ran here: end-of-stream answers come from the local
        # record, no head read
        st = self._gen_local.get(task_id)
        if st is None:
            st = self.gcs.gen_get(task_id)
        if st is not None and st["done"] and index >= (st["count"] or 0):
            if st["error"] is not None:
                self._reply(conn_key, P.INFO_REPLY,
                            (req_id, ("error", st["error"])))
            else:
                self._reply(conn_key, P.INFO_REPLY,
                            (req_id, ("end", st["count"])))
            return
        self._gen_waiters.setdefault((task_id, index), []).append(
            (conn_key, req_id))

    def _resolve_gen_end_waiters(self, task_id) -> None:
        """Answer parked waiters whose index is at/after the now-known
        end of a stream that terminated here (death/error path)."""
        lg = self._gen_local.get(task_id)
        if lg is None or not lg["done"]:
            return
        count = lg["count"] or 0
        for (tid, index) in [k for k in self._gen_waiters
                             if k[0] == task_id and k[1] >= count]:
            for conn_key, req_id in self._gen_waiters.pop((tid, index)):
                if lg["error"] is not None:
                    self._reply(conn_key, P.INFO_REPLY,
                                (req_id, ("error", lg["error"])))
                else:
                    self._reply(conn_key, P.INFO_REPLY,
                                (req_id, ("end", count)))

    def _gen_consume(self, task_id, consumed: int) -> None:
        """Advance the consumer credit. Producer running HERE: relay the
        GEN_ACK straight to its worker — no head write, no pubsub round
        (the reference's credit flow is likewise worker<->owner). Remote
        producer: the head's GEN channel carries it over."""
        if task_id in self._running:
            if consumed > self._gen_consumed_cache.get(task_id, 0):
                self._gen_consumed_cache[task_id] = consumed
                self._relay_gen_ack(task_id, consumed)
            return
        lg = self._gen_local.get(task_id)
        if lg is not None and lg["done"]:
            return      # producer finished here: credit has no reader
        self.gcs.gen_consumed(task_id, consumed)

    def _gen_close(self, task_id) -> None:
        """Consumer finished with / dropped its generator: unblock the
        producer forever (credit -> infinity), drop parked waiters, and
        drop the control-plane stream record (a late gen_update from a
        still-running producer recreates it harmlessly — the worker's
        credit is already infinite)."""
        self._gen_consume(task_id, 1 << 62)
        for key in [k for k in self._gen_waiters if k[0] == task_id]:
            del self._gen_waiters[key]
        self._gen_local.pop(task_id, None)
        self.gcs.gen_drop(task_id)

    def _on_gen_published(self, payload) -> None:
        self._events.put(("gen_event", payload))

    def _on_gen_event(self, payload) -> None:
        task_id, kind, n = payload
        if kind == "consumed":
            # relay credit to the producer if it runs on this node; also
            # cache it — if the task hasn't STARTED here yet, the relay
            # happens on its first GEN_ITEM instead
            if n > self._gen_consumed_cache.get(task_id, 0):
                self._gen_consumed_cache[task_id] = n
            self._relay_gen_ack(task_id, n)
        elif kind == "done":
            # stream ended: answer parked waiters at/past the end (the
            # producing node answers from its local record — the head
            # read is only for streams that ran elsewhere)
            st = self._gen_local.get(task_id)
            if st is None:
                st = self.gcs.gen_get(task_id)
            if st is None:
                return
            for (tid, index) in [k for k in self._gen_waiters
                                 if k[0] == task_id and k[1] >= n]:
                for conn_key, req_id in self._gen_waiters.pop((tid, index)):
                    if st["error"] is not None:
                        self._reply(conn_key, P.INFO_REPLY,
                                    (req_id, ("error", st["error"])))
                    else:
                        self._reply(conn_key, P.INFO_REPLY,
                                    (req_id, ("end", n)))
        elif kind == "produced":
            # an item produced on ANOTHER node: its OBJECT publish may
            # have raced ahead of our waiter registration — re-check
            index = n - 1
            waiters = self._gen_waiters.get((task_id, index))
            if waiters:
                oid = ObjectID.for_gen_item(task_id, index)
                meta = self._lookup_object(oid)
                if meta is not None:
                    del self._gen_waiters[(task_id, index)]
                    for conn_key, req_id in waiters:
                        self._reply(conn_key, P.INFO_REPLY,
                                    (req_id, ("item", meta)))
                        self._gen_consume(task_id, index + 1)

    def _on_object_published(self, payload) -> None:
        oid, meta = payload
        self._events.put(("object_ready", oid, meta))

    def _on_object_ready(self, oid: ObjectID, meta: ObjectMeta) -> None:
        self._reconstructing.discard(oid)
        # resolve task dependencies
        for tid in self._dep_index.pop(oid, ()):  # noqa: B020
            rec = self._waiting_deps.get(tid)
            if rec is None:
                continue
            rec.deps[oid] = meta
            rec.remaining_deps.discard(oid)
            if not rec.remaining_deps:
                del self._waiting_deps[tid]
                if rec.kind == "actor_call_waiting":
                    rec.kind = "actor_call"
                    self._send_actor_call(rec)
                    self._unblock_actor_owner(rec.spec)
                else:
                    # pending-queue starvation is measured from HERE, not
                    # record creation — dep-wait time must not trigger an
                    # immediate locality-losing spillback
                    rec.queued_at = time.monotonic()
                    self._pending.append(rec)
        # resolve client waiters
        for waiter_id in list(self._obj_waiter_index.pop(oid, ())):
            waiter = (self._get_waiters.get(waiter_id)
                      or self._wait_waiters.get(waiter_id))
            if waiter is None:
                continue
            waiter.remaining.discard(oid)
            self._maybe_fire_waiter(waiter_id, waiter)
        if not self._in_batch:
            self._dispatch()

    def _fail_returns(self, spec: P.TaskSpec, exc: Exception) -> None:
        err = to_bytes(exc)
        for oid in spec.return_ids:
            meta = ObjectMeta(object_id=oid, size=len(err), error=err)
            self._seal_object(meta)
        if spec.num_returns == -1:
            # streaming task died mid-production: end the stream with the
            # error at the next unproduced index so consumers don't hang
            # — in BOTH the node-local record (owner-local consumers
            # probe it first) and the head's
            lg = self._gen_local.get(spec.task_id)
            produced = (lg or {}).get("produced")
            if produced is None:
                st = self.gcs.gen_get(spec.task_id)
                produced = (st or {}).get("produced", 0)
            if lg is not None:
                lg.update(done=True, count=produced, error=err)
            else:
                self._gen_local[spec.task_id] = {
                    "produced": produced, "done": True,
                    "count": produced, "error": err}
            self.gcs.gen_done(spec.task_id, produced, err)
            self._resolve_gen_end_waiters(spec.task_id)
        self.gcs.publish("TASK_FINISHED", {"task_id": spec.task_id,
                                           "ok": False})

    # ---------------------------------------------------------------- actors
    def _create_actor(self, spec: P.ActorSpec) -> None:
        try:
            self.gcs.register_actor(spec)
        except ValueError as e:
            # duplicate named actor: surface the error through the
            # creation ref instead of a half-registered phantom record
            if spec.creation_return_id:
                err = to_bytes(e)
                self._seal_object(ObjectMeta(
                    object_id=spec.creation_return_id, size=len(err),
                    error=err))
            return
        self._owned[ActorTaskIds.creation_task(spec)] = _OwnedTask(
            spec=self._creation_task_spec(spec), kind="actor_create",
            retries_left=0, actor_spec=spec)
        self._pin_submission(ActorTaskIds.creation_task(spec),
                             self._arg_refs(spec))
        self._route_actor(spec)

    def _probe_target(self, spec) -> Optional[NodeID]:
        """Where this spec would schedule right now (None = infeasible)."""
        strategy = spec.scheduling_strategy
        if isinstance(strategy, sched.PlacementGroupSchedulingStrategy):
            return self._pg_target_node(strategy)
        demand = (self._creation_demand(spec)
                  if isinstance(spec, P.ActorSpec) else spec.resources)
        return sched.pick_node(demand, strategy or sched.DEFAULT,
                               self._candidates(), self.node_id, self._rng)

    def _route_actor(self, spec: P.ActorSpec) -> None:
        target = self._probe_target(spec)
        if target is None:
            if not self._park_infeasible("actor", spec):
                self._fail_actor_infeasible(spec)
            return
        self.gcs.set_actor_state(spec.actor_id, ACTOR_PENDING, node_id=target)
        if target == self.node_id:
            self._local_create_actor(spec)
        else:
            peer = self._peer(target)
            if peer is None:
                self.gcs.set_actor_state(spec.actor_id, ACTOR_DEAD,
                                         reason="target node died")
                if spec.creation_return_id:
                    err = to_bytes(exceptions.ActorDiedError(
                        spec.actor_id, "target node died before creation"))
                    self._seal_object(ObjectMeta(
                        object_id=spec.creation_return_id, size=len(err),
                        error=err))
                return
            self._debit_route(target, spec.resources)
            peer.post_remote(("remote_actor_create", spec))

    def _fail_queued_actor_tasks(self, actor_id: ActorID,
                                 reason: str) -> None:
        """Fail every method call still queued for a dead actor."""
        q = self._actor_queues.get(actor_id)
        while q:
            qspec = q.popleft()
            self._fail_returns(qspec, exceptions.ActorDiedError(
                actor_id, reason))
        self._actor_blocked_owners.pop(actor_id, None)

    def _creation_task_spec(self, spec: P.ActorSpec) -> P.TaskSpec:
        return P.TaskSpec(
            task_id=ActorTaskIds.creation_task(spec),
            job_id=spec.job_id,
            name=f"{spec.name}.__init__",
            function_id=b"",
            args=spec.args, kwargs=spec.kwargs,
            num_returns=1,
            return_ids=[spec.creation_return_id] if spec.creation_return_id else [],
            resources=self._creation_demand(spec),
            scheduling_strategy=spec.scheduling_strategy)

    @staticmethod
    def _creation_demand(spec: P.ActorSpec) -> Dict[str, float]:
        """Resource demand of the actor CREATION task. Reference
        semantics (``actor.py:384``): an actor with no explicit
        resources charges 1 CPU while its __init__ runs — gating
        concurrent creations — and 0 afterwards (the charge is released
        in ``_actor_creation_done``). An EXPLICIT num_cpus=0 arrives as
        resources {"CPU": 0.0} and skips the implicit charge (0 for
        creation AND running) — a 0-CPU helper actor must be creatable
        on a saturated node or the busy actors waiting on it deadlock.
        PG-scheduled actors draw from their bundle, where an implicit
        CPU may not exist."""
        if spec.resources:
            return spec.resources
        if isinstance(spec.scheduling_strategy,
                      sched.PlacementGroupSchedulingStrategy):
            return {}
        return {"CPU": 1.0}

    def _local_create_actor(self, spec: P.ActorSpec) -> None:
        self._actors[spec.actor_id] = {
            "spec": spec, "worker_id": None, "state": ACTOR_PENDING,
            "restarts_left": spec.max_restarts, "no_restart": False,
        }
        self._actor_queues.setdefault(spec.actor_id, deque())
        tspec = self._creation_task_spec(spec)
        self._queue_local(tspec, "actor_create", actor_spec=spec)

    def _actor_creation_done(self, rec: _TaskRecord,
                             error: Optional[bytes]) -> None:
        spec = rec.actor_spec
        st = self._actors.get(spec.actor_id)
        if error is not None:
            if st:
                st["state"] = ACTOR_DEAD
            self._release_charge(rec)
            self.gcs.set_actor_state(spec.actor_id, ACTOR_DEAD,
                                     reason="creation task failed")
            # method calls queued while the actor was PENDING would hang
            # forever otherwise
            self._fail_queued_actor_tasks(spec.actor_id,
                                          "actor creation failed")
            w = self._workers.get(rec.worker_id)
            if w is not None:
                w.actor_id = None
                self._mark_idle(w)
            return
        # actor keeps its resource charge (and TPU slots) for its
        # lifetime — except the implicit creation-only 1 CPU (see
        # _creation_demand), which is returned now that __init__ is done
        if st is not None:
            st["state"] = ACTOR_ALIVE
            st["worker_id"] = rec.worker_id
            st["pg_key"] = rec.pg_key
            if spec.resources:
                st["charge"] = rec.charge
                st["accel_ids"] = rec.accel_ids
                rec.accel_ids = None   # ownership moved: rec release
                rec.charge = None      # must not double-return them
            else:
                self._release_charge(rec)
                st["charge"] = None
                st["accel_ids"] = None
        w = self._workers.get(rec.worker_id)
        if w is not None:
            w.task = None
        self.gcs.set_actor_state(spec.actor_id, ACTOR_ALIVE,
                                 node_id=self.node_id)
        self._flush_actor_queue(spec.actor_id)

    def _submit_actor_task(self, spec: P.TaskSpec) -> None:
        telemetry.counter_inc(telemetry.M_TASKS_SUBMITTED, 1.0, self._mtags)
        self._owned[spec.task_id] = _OwnedTask(
            spec=spec, kind="actor_call", retries_left=spec.max_retries)
        self._pin_submission(spec.task_id, self._arg_refs(spec))
        rec = self.gcs.get_actor(spec.actor_id)
        if rec is None or rec.state == ACTOR_DEAD:
            self._fail_returns(spec, exceptions.ActorDiedError(
                spec.actor_id, rec.death_reason if rec else "unknown actor"))
            return
        if rec.state == ACTOR_RESTARTING and rec.node_id is None:
            # reroute window after a node death: no host exists yet.
            # Park until placement (or death) — failing now would turn a
            # survivable restart into a terminal ActorDiedError
            self._reroute_parked.setdefault(
                spec.actor_id, []).append(spec)
            return
        owned = self._owned[spec.task_id]
        owned.assigned_node = rec.node_id
        if rec.node_id is not None:
            self._record_task_origin(spec.task_id, rec.node_id)
        if rec.node_id == self.node_id or rec.node_id is None:
            self._local_actor_task(spec)
        else:
            peer = self._peer(rec.node_id)
            if peer is None:
                self._fail_returns(spec, exceptions.ActorDiedError(
                    spec.actor_id, "actor node is dead"))
                return
            peer.post_remote(("remote_actor_task", spec))

    def _local_actor_task(self, spec: P.TaskSpec) -> None:
        st = self._actors.get(spec.actor_id)
        if st is None or st["state"] == ACTOR_DEAD:
            reason = st and "actor is dead" or "unknown actor"
            self._fail_returns(spec, exceptions.ActorDiedError(
                spec.actor_id, reason))
            return
        self._actor_queues[spec.actor_id].append(spec)
        if st["state"] == ACTOR_ALIVE:
            self._flush_actor_queue(spec.actor_id)

    def _flush_actor_queue(self, actor_id: ActorID) -> None:
        st = self._actors.get(actor_id)
        q = self._actor_queues.get(actor_id)
        if st is None or q is None or st["state"] != ACTOR_ALIVE:
            return
        w = self._workers.get(st["worker_id"])
        if w is None or w.conn is None:
            return
        blocked = self._actor_blocked_owners.setdefault(actor_id, set())
        held = []            # calls parked behind a same-owner dep wait
        while q:
            spec = q.popleft()
            if spec.owner_id in blocked:
                # an earlier call from this submitter is dep-waiting: a
                # stateful actor must not observe call N+1 before call N
                held.append(spec)
                continue
            rec = _TaskRecord(spec=spec, kind="actor_call", worker_id=w.worker_id)
            # resolve deps inline; actor calls with unresolved deps wait
            unresolved = False
            for slot, val in list(spec.args) + list(spec.kwargs.values()):
                if slot == "r":
                    meta = self._lookup_object(val)
                    if meta is None:
                        unresolved = True
                        self._add_dep(rec, val)
                    else:
                        rec.deps[val] = meta
            if unresolved:
                self._waiting_deps[spec.task_id] = rec
                rec.kind = "actor_call_waiting"
                blocked.add(spec.owner_id)
                continue
            self._send_actor_call(rec)
        if held:
            q.extendleft(reversed(held))

    def _unblock_actor_owner(self, spec: P.TaskSpec) -> None:
        """A dep-waiting call from this submitter left the wait state
        (sent, failed, or cancelled): release the calls held behind it."""
        blocked = self._actor_blocked_owners.get(spec.actor_id)
        if blocked is not None and spec.owner_id in blocked:
            blocked.discard(spec.owner_id)
            self._flush_actor_queue(spec.actor_id)

    def _send_actor_call(self, rec: _TaskRecord) -> None:
        st = self._actors.get(rec.spec.actor_id)
        if st is None or st["state"] == ACTOR_DEAD:
            self._fail_returns(rec.spec, exceptions.ActorDiedError(
                rec.spec.actor_id, "actor is dead"))
            return
        if st["state"] != ACTOR_ALIVE:
            # head of the queue, not tail: this call is older than any
            # same-owner call already queued (it blocked them while
            # dep-waiting), and per-owner order must survive a restart
            self._actor_queues[rec.spec.actor_id].appendleft(rec.spec)
            return
        w = self._workers.get(st["worker_id"])
        if w is None or w.conn is None:
            self._actor_queues[rec.spec.actor_id].appendleft(rec.spec)
            return
        self._running[rec.spec.task_id] = rec
        self._record_event(rec.spec, "RUNNING")
        self._pin_deps(rec)
        rec.spec.accel_ids = st.get("accel_ids")
        # seq 0: actor calls are never leased/returned, but the EXECUTE
        # tuple shape is uniform
        self._send_execute(w, ("actor_call", rec.spec, rec.deps, None, 0))

    def _kill_actor(self, actor_id: ActorID, no_restart: bool) -> None:
        rec = self.gcs.get_actor(actor_id)
        if rec is None:
            return
        if rec.node_id == self.node_id or rec.node_id is None:
            self._local_kill_actor(actor_id, no_restart)
        else:
            peer = self._peer(rec.node_id)
            if peer is not None:
                peer.post_remote(("remote_kill_actor", actor_id, no_restart))

    def _local_kill_actor(self, actor_id: ActorID, no_restart: bool,
                          reason: str = "killed via kill()") -> None:
        st = self._actors.get(actor_id)
        if st is None:
            return
        st["no_restart"] = st["no_restart"] or no_restart
        w = self._workers.get(st.get("worker_id"))
        if w is not None and w.proc is not None:
            try:
                w.proc.kill()
            except OSError:
                pass
        else:
            self._handle_actor_death(actor_id, reason)

    def _handle_actor_death(self, actor_id: ActorID, reason: str) -> None:
        st = self._actors.get(actor_id)
        if st is None:
            return
        can_restart = (st["restarts_left"] != 0) and not st["no_restart"]
        self.events.emit(
            "WARNING" if can_restart else "ERROR", "ACTOR_DEATH", reason,
            actor_id=actor_id.hex(), will_restart=can_restart)
        # fail tasks currently running on the actor
        for tid, rec in list(self._running.items()):
            if rec.spec.actor_id == actor_id:
                del self._running[tid]
                self._unpin_deps(rec)
                self._fail_returns(rec.spec, exceptions.ActorDiedError(
                    actor_id, reason))
        self._release_actor_charge(st)
        if can_restart:
            if st["restarts_left"] > 0:
                st["restarts_left"] -= 1
            st["state"] = ACTOR_RESTARTING
            self.gcs.set_actor_state(actor_id, ACTOR_RESTARTING,
                                     node_id=self.node_id,
                                     count_restart=True)
            spec = st["spec"]
            tspec = self._creation_task_spec(spec)
            # The creation ref is single-use: keep it only if the first
            # creation never sealed it (worker died mid-__init__), so a
            # waiter on the ready-ref unblocks when the restart completes.
            if (spec.creation_return_id
                    and self._object_exists(spec.creation_return_id)):
                tspec.return_ids = []
            self._queue_local(tspec, "actor_create", actor_spec=spec)
        else:
            st["state"] = ACTOR_DEAD
            self.gcs.set_actor_state(actor_id, ACTOR_DEAD, reason=reason)
            # Seal the creation ref with the death error if it was never
            # sealed — otherwise a driver waiting on the ready-ref hangs
            # forever. (A ref already sealed by a successful __init__ must
            # not be overwritten in the directory.)
            spec = st["spec"]
            if (spec.creation_return_id
                    and not self._object_exists(spec.creation_return_id)):
                self._fail_returns(self._creation_task_spec(spec),
                                   exceptions.ActorDiedError(actor_id, reason))
            self._fail_queued_actor_tasks(actor_id, reason)

    def _release_actor_charge(self, st: dict) -> None:
        """Return a live actor's resource charge to the pool it came from —
        the node's free set or its placement-group bundle reservation."""
        charge = st.get("charge")
        if not charge:
            return
        st["charge"] = None
        with self._res_lock:
            pg_key = st.get("pg_key")
            if pg_key is not None:
                pool = self.pg_reservations.get(pg_key)
                if pool is not None:
                    sched.add(pool, charge)
            else:
                sched.add(self.resources_available, charge)
            st["accel_ids"] = self._return_tpu_slots(st.get("accel_ids"))

    def _on_actor_event(self, payload) -> None:
        if payload.get("state") == ACTOR_DEAD:
            self._events.put(("actor_dead", payload["actor_id"],
                              payload.get("reason", "")))
        elif payload.get("reroute"):
            self._events.put(("actor_reroute", payload["actor_id"]))
        if payload["actor_id"] in self._reroute_parked:
            # placement progressed (or death became final): re-drive the
            # calls parked during the reroute window
            self._events.put(("actor_parked_flush", payload["actor_id"]))

    def _flush_parked_actor_calls(self, actor_id: ActorID) -> None:
        for spec in self._reroute_parked.pop(actor_id, []):
            # re-enters the normal path: re-parks if still unplaced,
            # fails with the real death reason if the restart lost
            self._submit_actor_task(spec)

    def _reroute_actor(self, actor_id: ActorID) -> None:
        """Re-create a restartable actor whose node died. All nodes see
        the reroute event; the GCS claim admits exactly one."""
        try:
            orig_spec = self.gcs.claim_actor_reroute(actor_id)
        except Exception:   # noqa: BLE001 — plane unreachable: give up
            return
        if orig_spec is None:
            return
        try:
            import copy
            spec = copy.copy(orig_spec)
            rec = self.gcs.get_actor(actor_id)
            if spec.max_restarts >= 0 and rec is not None:
                # the new host's restart budget excludes restarts already
                # consumed (worker deaths and node deaths both count)
                spec.max_restarts = max(0, spec.max_restarts
                                        - rec.num_restarts)
            if (spec.creation_return_id
                    and self._object_exists(spec.creation_return_id)):
                # ready-ref already sealed by the first creation: the
                # re-creation must not seal it again
                spec.creation_return_id = None
            self.events.warning(
                "ACTOR_REROUTE", "restarting actor from a dead node",
                actor_id=actor_id.hex())
            self._route_actor(spec)
        except BaseException:
            # the claim is exactly-once: losing the spec here would
            # strand the actor in RESTARTING forever — hand it back so
            # another (or a later) claimant can retry
            try:
                self.gcs.requeue_actor_reroute(actor_id, orig_spec)
            except Exception:   # noqa: BLE001 — plane gone too
                pass
            raise

    def _on_remote_actor_dead(self, actor_id: ActorID, reason: str) -> None:
        """Owner-side: fail owned in-flight calls to an actor that died on
        another node (our local running set doesn't cover those)."""
        for tid, owned in list(self._owned.items()):
            if (owned.kind == "actor_call" and not owned.done
                    and owned.spec.actor_id == actor_id
                    and owned.assigned_node != self.node_id):
                owned.done = True
                self._fail_returns(owned.spec,
                                   exceptions.ActorDiedError(actor_id, reason))

    # --------------------------------------------------------- cancellation
    def _cancel_task(self, task_id: TaskID, force: bool) -> None:
        owned = self._owned.get(task_id)
        if owned is None or owned.done:
            return
        target = owned.assigned_node
        if target == self.node_id or target is None:
            self._local_cancel(task_id, force)
        else:
            peer = self._peer(target)
            if peer is not None:
                peer.post_remote(("remote_cancel", task_id, force))

    def _local_cancel(self, task_id: TaskID, force: bool) -> None:
        rec = self._waiting_deps.pop(task_id, None)
        if rec is not None and rec.kind == "actor_call_waiting":
            self._unblock_actor_owner(rec.spec)
        if rec is None:
            for r in self._pending:
                if r.spec.task_id == task_id:
                    rec = r
                    r.cancelled = True
                    # purge immediately: a cancelled rec parked behind a
                    # non-fitting bucket head would otherwise sit in the
                    # queue forever, feeding phantom demand to the
                    # autoscaler via pending_demand()
                    self._pending.remove(r)
                    break
        if rec is not None:
            self._fail_returns(rec.spec, exceptions.TaskCancelledError(task_id))
            return
        rec = self._running.get(task_id)
        if rec is not None and rec.worker_id is not None:
            w = self._workers.get(rec.worker_id)
            if w is not None and rec is not w.task and rec in w.pipeline:
                # leased-but-not-running: a signal would hit the wrong
                # task; tell the worker to skip it when its turn comes
                # and fail the returns here (the skip reply is
                # meta-less)
                rec.cancelled = True
                w.pipeline.remove(rec)
                self._running.pop(task_id, None)
                self._unpin_deps(rec)
                if w.conn is not None:
                    try:
                        w.conn.send((P.CANCEL_QUEUED, task_id))
                    except OSError:
                        pass
                self._fail_returns(rec.spec,
                                   exceptions.TaskCancelledError(task_id))
                return
            if w is not None and w.proc is not None:
                import signal
                try:
                    w.proc.send_signal(
                        signal.SIGKILL if force else signal.SIGINT)
                except OSError:
                    pass

    # ------------------------------------------------------------- get/wait
    def _get_objects(self, conn_key: int, req_id: int,
                     object_ids: List[ObjectID],
                     timeout: Optional[float],
                     fetch: bool = False) -> None:
        waiter = _Waiter(req_id=req_id, conn_key=conn_key,
                         object_ids=object_ids, fetch=fetch)
        for oid in object_ids:
            if not self._object_exists(oid):
                waiter.remaining.add(oid)
                self._maybe_reconstruct(oid)
        n_miss = len(waiter.remaining)
        if n_miss:
            telemetry.counter_inc(telemetry.M_STORE_MISSES,
                                  float(n_miss), self._mtags)
        if len(object_ids) > n_miss:
            telemetry.counter_inc(telemetry.M_STORE_HITS,
                                  float(len(object_ids) - n_miss),
                                  self._mtags)
        if not waiter.remaining:
            self._fire_get(waiter)
            return
        waiter_id = self._next_waiter
        self._next_waiter += 1
        self._get_waiters[waiter_id] = waiter
        for oid in waiter.remaining:
            self._obj_waiter_index.setdefault(oid, set()).add(waiter_id)
        if timeout is not None:
            waiter.timer = threading.Timer(
                timeout, lambda: self._events.put(
                    ("timer", lambda: self._timeout_get(waiter_id))))
            waiter.timer.daemon = True
            waiter.timer.start()

    def _maybe_fire_waiter(self, waiter_id: int, waiter: _Waiter) -> None:
        if waiter_id in self._get_waiters:
            if not waiter.remaining:
                del self._get_waiters[waiter_id]
                if waiter.timer:
                    waiter.timer.cancel()
                self._fire_get(waiter)
        elif waiter_id in self._wait_waiters:
            ready = len(waiter.object_ids) - len(waiter.remaining)
            if ready >= waiter.num_returns:
                del self._wait_waiters[waiter_id]
                if waiter.timer:
                    waiter.timer.cancel()
                self._fire_wait(waiter)

    def _fire_get(self, waiter: _Waiter) -> None:
        metas = [self._lookup_object(oid) for oid in waiter.object_ids]
        served = sum(m.size for m in metas if m is not None)
        if served:
            telemetry.counter_inc(telemetry.M_STORE_GET_BYTES,
                                  float(served), self._mtags)
        if waiter.fetch:
            # Payload copies + frame pickling for a wire driver can be
            # hundreds of MB; do them off the dispatcher (Connection.send
            # is thread-safe), mirroring why puts live in _DIRECT_OPS.
            threading.Thread(
                target=self._fire_get_fetch,
                args=(waiter, metas), daemon=True,
                name="rtpu-wire-fetch").start()
            return
        self._reply_batched(waiter.conn_key, P.GET_REPLY,
                            (waiter.req_id, metas))

    def _fire_get_fetch(self, waiter: _Waiter, metas) -> None:
        wire = [self._wire_meta(oid, meta)
                for oid, meta in zip(waiter.object_ids, metas)]
        self._reply(waiter.conn_key, P.GET_REPLY, (waiter.req_id, wire))

    def _wire_meta(self, oid: ObjectID,
                   meta: Optional[ObjectMeta]) -> Optional[ObjectMeta]:
        """Meta with the payload inlined, for drivers that share no
        /dev/shm with this host (Ray-Client-equivalent data plane).
        ``meta`` comes from ``_lookup_object``, which has already adopted
        cross-host payloads into our store via the peer pull. Never
        raises: a None return makes the client surface ObjectLostError."""
        if meta is None or meta.inline is not None or meta.error is not None:
            return meta
        try:
            res = self.store.read_payload(oid)
            if res is not None:
                meta, data = res
                if data is None:         # store held it inline / as error
                    return meta
            else:
                # same-host sibling store (in-process cluster): attach by
                # segment name / arena path
                data = object_store.read_wire_bytes(meta)
        except Exception:                # noqa: BLE001 — must always reply
            return None
        if data is None:
            return None
        return ObjectMeta(object_id=oid, size=meta.size, inline=data)

    def _drop_waiter_index(self, waiter_id: int, waiter: _Waiter) -> None:
        for oid in waiter.remaining:
            ids = self._obj_waiter_index.get(oid)
            if ids is not None:
                ids.discard(waiter_id)
                if not ids:
                    del self._obj_waiter_index[oid]

    def _timeout_get(self, waiter_id: int) -> None:
        waiter = self._get_waiters.pop(waiter_id, None)
        if waiter is None:
            return
        self._drop_waiter_index(waiter_id, waiter)
        err = to_bytes(exceptions.GetTimeoutError(
            f"objects not ready within timeout: "
            f"{[o.hex()[:12] for o in waiter.remaining]}"))
        self._reply(waiter.conn_key, P.ERROR_REPLY, (waiter.req_id, err))

    def _wait_objects(self, conn_key: int, req_id: int,
                      object_ids: List[ObjectID], num_returns: int,
                      timeout: Optional[float]) -> None:
        waiter = _Waiter(req_id=req_id, conn_key=conn_key,
                         object_ids=object_ids, num_returns=num_returns)
        for oid in object_ids:
            if not self._object_exists(oid):
                waiter.remaining.add(oid)
                self._maybe_reconstruct(oid)
        ready = len(object_ids) - len(waiter.remaining)
        if ready >= num_returns or timeout == 0:
            self._fire_wait(waiter)
            return
        waiter_id = self._next_waiter
        self._next_waiter += 1
        self._wait_waiters[waiter_id] = waiter
        for oid in waiter.remaining:
            self._obj_waiter_index.setdefault(oid, set()).add(waiter_id)
        if timeout is not None:
            waiter.timer = threading.Timer(
                timeout, lambda: self._events.put(
                    ("timer", lambda: self._timeout_wait(waiter_id))))
            waiter.timer.daemon = True
            waiter.timer.start()

    def _fire_wait(self, waiter: _Waiter) -> None:
        ready = [oid for oid in waiter.object_ids
                 if oid not in waiter.remaining]
        pending = [oid for oid in waiter.object_ids if oid in waiter.remaining]
        self._reply_batched(waiter.conn_key, P.WAIT_REPLY,
                            (waiter.req_id, ready, pending))

    def _timeout_wait(self, waiter_id: int) -> None:
        waiter = self._wait_waiters.pop(waiter_id, None)
        if waiter is None:
            return
        self._drop_waiter_index(waiter_id, waiter)
        self._fire_wait(waiter)

    # ------------------------------------------------------- failure paths
    def _on_conn_closed(self, key: int) -> None:
        conn = self._conns.pop(key, None)
        self._driver_conn_keys.discard(key)
        # retire the collective route only if it still points at THIS
        # conn (a restarted process re-registers under the same id)
        cwid = self._conn_coll_wid.pop(key, None)
        if cwid is not None and self._coll_conns.get(cwid) is conn:
            self._coll_conns.pop(cwid, None)
        # arena Creates this connection never sealed are garbage now
        self.store.reclaim_unsealed(key)
        # a dead consumer's parked stream requests: drop the waiters and
        # release the producers it was pacing (synthesized GEN_CLOSE)
        dead_streams = set()
        for (tid, index), waiters in list(self._gen_waiters.items()):
            kept = [(ck, rid) for ck, rid in waiters if ck != key]
            if len(kept) != len(waiters):
                dead_streams.add(tid)
                if kept:
                    self._gen_waiters[(tid, index)] = kept
                else:
                    del self._gen_waiters[(tid, index)]
        for tid in dead_streams:
            self._gen_close(tid)
        # the process died with references: drop them all at once
        held = self._conn_refs.pop(key, None)
        if held:
            try:
                self.gcs.drop_all_refs(self._holder_id(key), list(held))
            except Exception:
                pass
        wid = self._conn_worker.pop(key, None)
        if wid is None:
            return
        w = self._workers.pop(wid, None)
        if w is None:
            return
        if self._stopped.is_set():
            return
        w.state = "DEAD"
        try:
            self._idle.remove(wid)
        except ValueError:
            pass
        if w.actor_id is not None:
            st = self._actors.get(w.actor_id)
            # fail the creation task if it was in flight
            rec = w.task
            if rec is not None and rec.kind == "actor_create":
                self._running.pop(rec.spec.task_id, None)
                self._unpin_deps(rec)
                self._release_charge(rec)
            self._handle_actor_death(
                w.actor_id,
                "actor worker killed by the memory monitor (node out of "
                "memory)" if w.oom_victim else "actor worker process died")
            return
        # the running task AND any leased pipeline behind it died with
        # the process; only the running one holds a charge
        for rec in ([w.task] if w.task is not None else []) \
                + list(w.pipeline):
            self._running.pop(rec.spec.task_id, None)
            self._unpin_deps(rec)
            self._release_charge(rec)
            if w.oom_victim and rec.oom_retries_left > 0:
                # OOM retries are a separate budget: the task did nothing
                # wrong, the node ran out of memory under it
                rec.oom_retries_left -= 1
                rec.worker_id = None
                rec.charge = None
                self._pending.append(rec)
            elif not w.oom_victim and rec.retries_left > 0:
                rec.retries_left -= 1
                rec.worker_id = None
                rec.charge = None
                self._pending.append(rec)
            elif w.oom_victim:
                self._fail_returns(rec.spec, exceptions.OutOfMemoryError(
                    f"task {rec.spec.name} was killed by the memory "
                    f"monitor to relieve node memory pressure "
                    f"(usage >= {CONFIG.memory_usage_threshold:.0%}); "
                    f"oom retries exhausted"))
            else:
                self._fail_returns(rec.spec, exceptions.WorkerCrashedError(
                    f"worker died while running {rec.spec.name}"))
        w.pipeline.clear()
        if not self._in_batch:
            self._dispatch()

    def _on_node_event(self, payload) -> None:
        if payload.get("state") == "DEAD" and payload["node_id"] != self.node_id:
            self._events.put(("node_dead", payload["node_id"]))
        elif payload.get("state") == "ALIVE" and self._infeasible:
            # fresh capacity (autoscaler scale-up): retry parked work
            self._events.put(("timer", self._retry_infeasible))

    def _on_task_finished(self, payload) -> None:
        self._events.put(("task_finished", payload["task_id"]))

    def _on_node_dead(self, node_id: NodeID) -> None:
        """Owner-side recovery: resubmit or fail tasks we forwarded to a node
        that died (reference: lease failure + ``RetryTaskIfPossible``), and
        rebuild lost objects that local waiters/deps still need
        (``object_recovery_manager.h:90``)."""
        # every surviving node observes the same death: only the node
        # co-located with the control plane publishes it cluster-wide
        self.events.warning("NODE_DEATH", "peer node died",
                            dead_node_id=node_id.hex(),
                            local_only=not isinstance(
                                self.gcs, GlobalControlPlane))
        peer = self._peers.pop(node_id, None)
        if peer is not None:
            peer.close()
        for oid in set(self._obj_waiter_index) | set(self._dep_index):
            self._maybe_reconstruct(oid)   # claim gate filters non-lost
        for tid, owned in list(self._owned.items()):
            if owned.done or owned.assigned_node != node_id:
                continue
            if owned.kind == "task":
                if owned.retries_left > 0:
                    owned.retries_left -= 1
                    self._route_task(owned.spec)
                else:
                    self._fail_returns(owned.spec,
                                       exceptions.WorkerCrashedError(
                                           f"node {node_id} died"))
                    owned.done = True
            elif owned.kind == "actor_call":
                self._fail_returns(owned.spec, exceptions.ActorDiedError(
                    owned.spec.actor_id, f"node {node_id} died"))
                owned.done = True

    # -------------------------------------------------------------- pg/info
    def _create_pg(self, conn_key: int, payload) -> None:
        req_id, spec = payload
        assignment = sched.pack_bundles(spec.bundles, spec.strategy,
                                        self._candidates())
        if assignment is None:
            # make the gang demand visible to the autoscaler; refreshed
            # on every client retry, cleared on success/removal
            self.gcs.register_pending_pg(spec)
            self._reply(conn_key, P.INFO_REPLY, (req_id, None))
            return
        ok = True
        reserved = []
        for idx, (bundle, nid) in enumerate(zip(spec.bundles, assignment)):
            peer = self._peer(nid)
            if peer is None or not peer.reserve_bundle((spec.pg_id, idx),
                                                       bundle):
                ok = False
                break
            reserved.append((peer, (spec.pg_id, idx)))
        if not ok:
            for peer, key in reserved:
                peer.release_bundle(key)
            self._reply(conn_key, P.INFO_REPLY, (req_id, None))
            return
        self.gcs.register_pg(spec, assignment)
        self.gcs.clear_pending_pg(spec.pg_id)
        self._reply(conn_key, P.INFO_REPLY, (req_id, assignment))

    def _remove_pg(self, pg_id) -> None:
        self.gcs.clear_pending_pg(pg_id)
        rec = self.gcs.remove_pg(pg_id)
        if rec is None:
            return
        for idx, nid in enumerate(rec["assignment"]):
            peer = self._peer(nid)
            if peer is not None:
                peer.release_bundle((pg_id, idx))

    def _peer_stats(self, info, what,
                    timeout: Optional[float] = None) -> Any:
        """Stats from any alive node: in-process or over the wire.
        ``timeout`` only applies to the wire path (debug collections
        outlive the default lease timeout)."""
        if info.service is not None:
            return (None if info.service.dead
                    else info.service.node_stats(what))
        peer = self._peer(info.node_id)
        if peer is None:
            return None
        if isinstance(peer, _RemotePeer):
            return peer.node_stats(what, timeout=timeout)
        return peer.node_stats(what)

    def _cluster_info(self, what: str) -> Any:
        if what == "resources_total":
            return self.gcs.cluster_resources()
        if what == "resources_available":
            out: Dict[str, float] = {}
            for info in self.gcs.alive_nodes():
                avail = self._peer_stats(info, "available")
                for k, v in (avail or {}).items():
                    out[k] = out.get(k, 0.0) + v
            return out
        if what == "nodes":
            # resources_available / pending_shapes expose the gossiped
            # view the router consumes (RaySyncer-equivalent): tests and
            # operators can poll the EXACT staleness the scheduler sees
            return [{"node_id": n.node_id, "address": n.address,
                     "resources": n.resources_total, "alive": n.alive,
                     "labels": n.labels,
                     "resources_available": dict(n.resources_available
                                                 or {}),
                     "pending_shapes": list(n.pending_shapes or ())}
                    for n in self.gcs.nodes_snapshot()]
        if what == "store_stats":
            return self.store.stats()
        if what == "workers":
            out = []
            for info in self.gcs.alive_nodes():
                out.extend(self._peer_stats(info, "workers") or [])
            return out
        if what == "config":
            return CONFIG.dump()
        return None

    def _state_query(self, what: str, filters) -> Any:
        if what == "tasks":
            return [ev.__dict__ for ev in self.gcs.list_task_events()]
        if what == "actors":
            return [{"actor_id": aid, "state": rec.state,
                     "name": rec.spec.registered_name,
                     "class_name": rec.spec.name,
                     "node_id": rec.node_id,
                     "num_restarts": rec.num_restarts,
                     "max_restarts": rec.spec.max_restarts}
                    for aid, rec in self.gcs.actors_snapshot()]
        if what == "objects":
            return self._memory_objects()
        if what == "memory":
            # full introspection payload: enriched object rows + current
            # leak findings + per-node store stats
            rows, leaks = self._memory_objects(with_leaks=True)
            stores = {}
            for info in self.gcs.alive_nodes():
                st = self._peer_stats(info, "store")
                if st:
                    stores[info.node_id.hex()] = st
            return {"objects": rows, "leaks": leaks, "stores": stores}
        if what == "placement_groups":
            return [{"pg_id": pid, "state": rec["state"],
                     "bundles": rec["spec"].bundles,
                     "strategy": rec["spec"].strategy}
                    for pid, rec in self.gcs.pgs_snapshot()]
        if what == "jobs":
            return [{"job_id": rec.job_id, "driver_pid": rec.driver_pid,
                     "start_time": rec.start_time,
                     "end_time": rec.end_time}
                    for rec in self.gcs.jobs_snapshot()]
        if what == "cluster_events":
            # full ring: the state API applies filters BEFORE its limit,
            # so a server-side cap would hide older matching rows
            return self.gcs.list_cluster_events(limit=10**9)
        if what == "events_stats":
            # ring occupancy + the eviction counter behind
            # rtpu_events_evicted_total (silent history loss, observable)
            return self.gcs.events_stats()
        if what == "lifecycle":
            return self.gcs.lifecycle_snapshot()
        if what == "metrics_history":
            f = filters or {}
            return self.gcs.metrics_history_query(
                name=f.get("name"), tags=f.get("tags"),
                window=f.get("window"), step=f.get("step"))
        if what == "metrics_history_dump":
            return self.gcs.metrics_history_dump()
        if what == "spans":
            return self.gcs.list_spans(limit=10**9)
        if what == "metrics":
            # merged cluster-wide telemetry; flush our own shards first
            # so a scrape right after local activity is never stale
            telemetry.flush()
            return self.gcs.metrics_snapshot()
        if what == "reconstruct_stats":
            # lineage-reconstruction claim counts per object (the chaos
            # tests assert a lost chain was rebuilt exactly once)
            return self.gcs.reconstruct_stats()
        return None

    def _memory_objects(self, with_leaks: bool = False):
        """Enriched object ledger rows: the control plane's consistent
        snapshot (size, callsite, creator, ref types) merged with each
        node's store-local pin/spill facts (one ``node_stats`` fan-out
        per query — an introspection surface, never a hot path)."""
        mem = self.gcs.memory_state() or {}
        rows = mem.get("objects") or []
        local: Dict[Any, tuple] = {}
        for info in self.gcs.alive_nodes():
            snap = self._peer_stats(info, "objects")
            if snap:
                local.update(snap)
        for row in rows:
            pinned, spilled = local.get(row["object_id"], (0, False))
            row["pinned_in_store"] = pinned
            row["spilled"] = spilled
            if pinned:
                row["ref_types"]["PINNED_IN_STORE"] = pinned
        if with_leaks:
            return rows, mem.get("leaks") or []
        return rows

    def _record_event(self, spec: P.TaskSpec, state: str,
                      pending_args: Optional[List[ObjectID]] = None) -> None:
        self.gcs.record_task_event(TaskEvent(
            task_id=spec.task_id, name=spec.name, state=state,
            node_id=self.node_id, timestamp=time.time(),
            is_actor_task=spec.actor_id is not None,
            # diagnosis inputs for the stall detector
            resources=dict(spec.resources) if spec.resources else None,
            actor_id=spec.actor_id,
            pending_args=pending_args))


def _user_sys_paths() -> List[str]:
    """sys.path entries added by the user/driver (script dir, cwd,
    test dirs) — interpreter-owned dirs (stdlib, site-packages) are
    excluded so they never shadow a pip runtime-env venv."""
    import site
    import sysconfig

    interp = set()
    for key in ("stdlib", "platstdlib", "purelib", "platlib"):
        try:
            interp.add(os.path.realpath(sysconfig.get_paths()[key]))
        except KeyError:
            pass
    for p in site.getsitepackages() + [site.getusersitepackages()]:
        interp.add(os.path.realpath(p))
    out = []
    for p in sys.path:
        if not p or not os.path.isdir(p):
            continue
        rp = os.path.realpath(p)
        if any(rp == d or rp.startswith(d + os.sep) for d in interp):
            continue
        if rp.startswith(os.path.realpath(sys.prefix) + os.sep):
            continue
        out.append(p)
    return out


class ActorTaskIds:
    """Deterministic creation-task id per actor."""

    @staticmethod
    def creation_task(spec: P.ActorSpec) -> TaskID:
        return TaskID(TaskID.KIND + spec.actor_id.binary()[1:])
