"""Structured cluster event log.

Reference analogue: the event framework (``src/ray/util/event.h`` —
RAY_EVENT macros writing structured JSON event files per component,
surfaced by ``ray list cluster-events``). Here: every node appends
JSONL records to ``<session>/events/`` AND publishes them to the
control plane's bounded ring, where ``state.api.list_cluster_events()``
reads them back. Events cover lifecycle facts a timeline of task states
can't express: node up/down, OOM kills, worker-start failures, actor
deaths with causes.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

from . import locksan

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")


class EventLogger:
    def __init__(self, session_dir: str, node_id_hex: str, gcs=None):
        self._dir = os.path.join(session_dir, "events")
        os.makedirs(self._dir, exist_ok=True)
        self._path = os.path.join(self._dir,
                                  f"events_{node_id_hex[:12]}.jsonl")
        self._node = node_id_hex
        self._gcs = gcs
        self._lock = locksan.lock("events.file")

    def emit(self, severity: str, label: str, message: str,
             local_only: bool = False, **fields: Any) -> None:
        """Append one structured event; never raises (observability must
        not take down the component it observes). ``local_only`` skips
        the control-plane publish — for facts every node observes
        simultaneously (a peer death), which would otherwise flood the
        bounded ring with N-1 duplicates."""
        rec = {
            "timestamp": time.time(),
            "severity": severity if severity in SEVERITIES else "INFO",
            "label": label,
            "message": message,
            "node_id": self._node,
            "pid": os.getpid(),
            **fields,
        }
        try:
            line = json.dumps(rec, default=str)
        except (TypeError, ValueError):
            return
        try:
            with self._lock, open(self._path, "a") as f:
                f.write(line + "\n")
        except OSError:
            pass
        if self._gcs is not None and not local_only:
            try:
                self._gcs.record_cluster_event(rec)
            except Exception:    # noqa: BLE001 — best-effort publish
                pass

    def info(self, label: str, message: str, **fields) -> None:
        self.emit("INFO", label, message, **fields)

    def warning(self, label: str, message: str, **fields) -> None:
        self.emit("WARNING", label, message, **fields)

    def error(self, label: str, message: str, **fields) -> None:
        self.emit("ERROR", label, message, **fields)
