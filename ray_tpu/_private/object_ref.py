"""ObjectRef: a future handle to an object in the distributed store.

Reference analogue: ``ObjectRef`` (``python/ray/includes/object_ref.pxi``).
Dumb by design — it holds only the id; resolution goes through the
process-global client so refs can be pickled into task args, stored inside
other objects, and reconstructed in any process of the cluster.
"""

from __future__ import annotations

import threading

from .ids import ObjectID, TaskID

# Per-thread capture of refs pickled into a value. A worker storing a
# task return activates this around serialization so the node can pin
# the CONTAINED objects until the return object itself is freed —
# without it, a ref that only lives inside a not-yet-deserialized
# return loses its last holder the moment the producer's locals die
# (reference analogue: borrowed-ref tracking inside returned values,
# ``reference_count.h``).
_capture = threading.local()


def begin_ref_capture() -> None:
    _capture.ids = []


def end_ref_capture() -> list:
    ids = getattr(_capture, "ids", None)
    _capture.ids = None
    return ids or []


class ObjectRef:
    """Distributed reference counting (reference: ``reference_count.h:61``
    local references): every live ObjectRef instance counts toward its
    process's local count for the object; the process tells its node on
    the 0→1 and 1→0 transitions, and the control plane frees the object
    when no process holds a reference and no submitted task uses it.
    Unpickling a ref (task args, values containing refs) registers the
    receiving process as a borrower automatically."""

    __slots__ = ("id", "_tracked")

    def __init__(self, object_id: ObjectID, _track: bool = True):
        self.id = object_id
        self._tracked = False
        if _track:
            from . import context
            client = context.current_client
            if client is not None:
                client.ref_incr(object_id)
                self._tracked = True

    def __del__(self):
        if self._tracked:
            try:
                from . import context
                client = context.current_client
                if client is not None:
                    client.ref_decr(self.id)
            except Exception:   # interpreter teardown / closed conn
                pass

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self) -> TaskID:
        """The task whose return this ref is. For ``put`` objects the result
        is a synthetic id that matches no submitted task (cancel is a no-op,
        as in the reference)."""
        return TaskID(TaskID.KIND + self.id.binary()[:15])

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        from . import context
        client = context.require_client()
        return client.as_future(self)

    def __await__(self):
        import asyncio
        from . import context
        client = context.require_client()
        return asyncio.wrap_future(client.as_future(self)).__await__()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        ids = getattr(_capture, "ids", None)
        if ids is not None:
            ids.append(self.id)
        return (ObjectRef, (self.id,))


class ObjectRefGenerator:
    """Consumer handle for a streaming task's dynamic returns
    (reference: ``_raylet.pyx:252`` ObjectRefGenerator). Iterating
    yields ObjectRefs one by one as the producer reports them; the item
    request is what paces the producer's backpressure window. Raises the
    task's error at the index where production broke; StopIteration at
    the stream end."""

    def __init__(self, task_id: TaskID):
        self.task_id = task_id
        self._index = 0
        self._count = None          # known stream length once ended
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        from . import context
        from . import serialization as ser
        if self._count is not None and self._index >= self._count:
            raise StopIteration
        client = context.require_client()
        status, payload = client.gen_next(self.task_id, self._index)
        if status == "item":
            ref = ObjectRef(payload.object_id)
            self._index += 1
            return ref
        # terminal: tell the node so it drops the stream record (a
        # long-lived cluster must not accumulate one per stream)
        self._close()
        if status == "end":
            self._count = payload
            raise StopIteration
        # error ends the stream too: a retried next() must raise
        # StopIteration locally, not park on the dropped record
        self._count = self._index
        raise ser.from_bytes(payload)       # status == "error"

    def _close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                from . import context
                client = context.current_client
                if client is not None:
                    client.gen_close(self.task_id)
            except Exception:   # teardown / closed conn
                pass

    def __del__(self):
        self._close()

    def __reduce__(self):
        # passing a generator between processes would need cross-owner
        # consumed-index coordination; the reference restricts this too
        raise TypeError(
            "ObjectRefGenerator is not picklable; iterate it in the "
            "process that called .remote(), passing the yielded "
            "ObjectRefs on instead")
