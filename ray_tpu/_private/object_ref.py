"""ObjectRef: a future handle to an object in the distributed store.

Reference analogue: ``ObjectRef`` (``python/ray/includes/object_ref.pxi``).
Dumb by design — it holds only the id; resolution goes through the
process-global client so refs can be pickled into task args, stored inside
other objects, and reconstructed in any process of the cluster.
"""

from __future__ import annotations

from .ids import ObjectID, TaskID


class ObjectRef:
    __slots__ = ("id",)

    def __init__(self, object_id: ObjectID):
        self.id = object_id

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self) -> TaskID:
        """The task whose return this ref is. For ``put`` objects the result
        is a synthetic id that matches no submitted task (cancel is a no-op,
        as in the reference)."""
        return TaskID(TaskID.KIND + self.id.binary()[:15])

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        from . import context
        client = context.require_client()
        return client.as_future(self)

    def __await__(self):
        import asyncio
        from . import context
        client = context.require_client()
        return asyncio.wrap_future(client.as_future(self)).__await__()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        return (ObjectRef, (self.id,))
