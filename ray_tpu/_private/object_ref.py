"""ObjectRef: a future handle to an object in the distributed store.

Reference analogue: ``ObjectRef`` (``python/ray/includes/object_ref.pxi``).
Dumb by design — it holds only the id; resolution goes through the
process-global client so refs can be pickled into task args, stored inside
other objects, and reconstructed in any process of the cluster.
"""

from __future__ import annotations

from .ids import ObjectID, TaskID


class ObjectRef:
    """Distributed reference counting (reference: ``reference_count.h:61``
    local references): every live ObjectRef instance counts toward its
    process's local count for the object; the process tells its node on
    the 0→1 and 1→0 transitions, and the control plane frees the object
    when no process holds a reference and no submitted task uses it.
    Unpickling a ref (task args, values containing refs) registers the
    receiving process as a borrower automatically."""

    __slots__ = ("id", "_tracked")

    def __init__(self, object_id: ObjectID, _track: bool = True):
        self.id = object_id
        self._tracked = False
        if _track:
            from . import context
            client = context.current_client
            if client is not None:
                client.ref_incr(object_id)
                self._tracked = True

    def __del__(self):
        if self._tracked:
            try:
                from . import context
                client = context.current_client
                if client is not None:
                    client.ref_decr(self.id)
            except Exception:   # interpreter teardown / closed conn
                pass

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self) -> TaskID:
        """The task whose return this ref is. For ``put`` objects the result
        is a synthetic id that matches no submitted task (cancel is a no-op,
        as in the reference)."""
        return TaskID(TaskID.KIND + self.id.binary()[:15])

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        from . import context
        client = context.require_client()
        return client.as_future(self)

    def __await__(self):
        import asyncio
        from . import context
        client = context.require_client()
        return asyncio.wrap_future(client.as_future(self)).__await__()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        return (ObjectRef, (self.id,))
