"""Black-box post-mortem bundles: capture everything a session knows
into one portable tar, replay it offline.

``rtpu debug-bundle`` (or an auto-capture on a terminal failure —
collective reform budget exhaustion, a memory-monitor OOM kill, driver
shutdown on an uncaught error) snapshots every observability surface
the runtime has — metrics + their retention history, cluster events,
lifecycle transitions, stacks, flight-recorder rings, access logs,
spans, the memory/provenance ledger, config + versions — as JSON
sections inside a ``.tar.gz`` with a versioned manifest. ``rtpu
autopsy <bundle>`` then rebuilds the doctor / coll-debug / serve-status
/ memory surfaces from the captured sections through the SAME pure
builders the live CLI uses, with no cluster running: a chaos casualty
leaves a corpse worth reading.

Reference analogue: the flight-recorder style "cluster state dump"
workflows around ``ray cluster-dump`` — scoped here to the surfaces
this runtime actually has, and made replayable instead of just
archived.

The section list is a REGISTRY: ``BUNDLE_SECTIONS`` (a pure literal)
must match the ``_capture_<name>`` functions below both ways —
``scripts/check_metrics.py`` lints the pairing exactly like the config
knob and metric registries, so a new surface can't silently miss the
bundle (or a dead section linger in the manifest).
"""

from __future__ import annotations

import io
import json
import os
import platform
import sys
import tarfile
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from . import locksan
from . import telemetry
from .config import CONFIG

BUNDLE_FORMAT_VERSION = 1
BUNDLE_KIND = "rtpu-debug-bundle"

# every surface a bundle captures, in manifest order (one <name>.json
# per section). Keep this a pure tuple literal: the lint reads it.
BUNDLE_SECTIONS = (
    "config",
    "nodes",
    "resources",
    "tasks",
    "actors",
    "objects",
    "memory",
    "jobs",
    "placement_groups",
    "events",
    "lifecycle",
    "spans",
    "metrics",
    "metrics_history",
    "stacks",
    "collectives",
    "flight_records",
    "serve",
    "serve_requests",
    "reconstruct_stats",
)

M_BUNDLES = telemetry.define(
    "counter", "rtpu_debug_bundles_total",
    "Post-mortem debug bundles captured, tagged by trigger reason "
    "(manual | oom_kill | collective_reform_exhausted | driver_error)")


class ClientSource:
    """Capture adapter over a connected ``CoreClient`` (driver/worker/
    CLI processes)."""

    kind = "client"

    def __init__(self, client):
        self._client = client

    def state_query(self, what: str, filters=None):
        return self._client.state_query(what, filters)

    def cluster_info(self, what: str):
        return self._client.cluster_info(what)

    def cluster_stacks(self, timeout_s: float):
        return self._client.cluster_stacks(timeout_s)

    def collective_health(self, timeout_s: float):
        return self._client.collective_health(timeout_s)

    def flight_records(self, timeout_s: float):
        return self._client.flight_records(timeout_s)

    def serve_requests(self, limit: int):
        from ..state import api as state_api
        return state_api.serve_requests(limit=limit, timeout_s=5.0)

    def emit_event(self, payload: dict) -> None:
        # the node's EventLogger owns the literal DEBUG_BUNDLE emit
        # (statically lintable); this process only relays
        self._client.send_profile_event("debug_bundle", payload)


class NodeSource:
    """Capture adapter over an in-process ``NodeService`` (the OOM-kill
    auto-capture runs on the node's own surfaces — no client needed)."""

    kind = "node"

    def __init__(self, node):
        self._node = node

    def state_query(self, what: str, filters=None):
        return self._node._state_query(what, filters)

    def cluster_info(self, what: str):
        return self._node._cluster_info(what)

    def cluster_stacks(self, timeout_s: float):
        return self._node.cluster_stacks(timeout_s)

    def collective_health(self, timeout_s: float):
        return self._node.collective_health(timeout_s)

    def flight_records(self, timeout_s: float):
        return self._node.collect_flight_records(timeout_s)

    def serve_requests(self, limit: int):
        return []       # access logs need a live actor client; skip

    def emit_event(self, payload: dict) -> None:
        rec = dict(payload)
        msg = str(rec.pop("message", "debug bundle captured"))
        self._node.events.info("DEBUG_BUNDLE", msg, **rec)


# ------------------------------------------------------- section capture

def _capture_config(src, timeout_s: float, ctx: dict):
    return {
        "config": CONFIG.dump(),
        "versions": {
            "python": sys.version.split()[0],
            "platform": platform.platform(),
            "ray_tpu": _pkg_version(),
        },
        "pid": os.getpid(),
        "argv": list(sys.argv),
    }


def _pkg_version() -> str:
    try:
        import importlib.metadata as _md
        return _md.version("ray-tpu")
    except Exception:   # noqa: BLE001 — dev checkout
        return "dev"


def _capture_nodes(src, timeout_s: float, ctx: dict):
    from ..state import api as state_api
    return state_api.shape_nodes(src.cluster_info("nodes") or [])


def _capture_resources(src, timeout_s: float, ctx: dict):
    return {"total": src.cluster_info("resources_total") or {},
            "available": src.cluster_info("resources_available") or {}}


def _capture_tasks(src, timeout_s: float, ctx: dict):
    from ..state import api as state_api
    return state_api.shape_tasks(src.state_query("tasks") or [])


def _capture_actors(src, timeout_s: float, ctx: dict):
    from ..state import api as state_api
    return state_api.shape_actors(src.state_query("actors") or [])


def _capture_objects(src, timeout_s: float, ctx: dict):
    from ..state import api as state_api
    return state_api.shape_objects(src.state_query("objects") or [])


def _capture_memory(src, timeout_s: float, ctx: dict):
    from ..state import api as state_api
    mem = src.state_query("memory") or {}
    return {"objects": state_api.shape_objects(mem.get("objects")),
            "leaks": state_api.shape_leaks(mem.get("leaks")),
            "stores": mem.get("stores") or {}}


def _capture_jobs(src, timeout_s: float, ctx: dict):
    rows = src.state_query("jobs") or []
    return [{**r, "job_id": (r["job_id"].hex()
                             if hasattr(r.get("job_id"), "hex")
                             else str(r.get("job_id")))}
            for r in rows]


def _capture_placement_groups(src, timeout_s: float, ctx: dict):
    from ..state import api as state_api
    return state_api.shape_placement_groups(
        src.state_query("placement_groups") or [])


def _capture_events(src, timeout_s: float, ctx: dict):
    return {"rows": src.state_query("cluster_events") or [],
            "stats": src.state_query("events_stats") or {}}


def _capture_lifecycle(src, timeout_s: float, ctx: dict):
    return src.state_query("lifecycle") or []


def _capture_spans(src, timeout_s: float, ctx: dict):
    return src.state_query("spans") or []


def _capture_metrics(src, timeout_s: float, ctx: dict):
    from ..state import api as state_api
    rows = state_api.shape_metrics(src.state_query("metrics") or {})
    # stash for later sections (serve) — ONE metrics fetch per capture
    ctx["metrics_rows"] = rows
    return rows


def _capture_metrics_history(src, timeout_s: float, ctx: dict):
    return src.state_query("metrics_history_dump") or {}


def _capture_stacks(src, timeout_s: float, ctx: dict):
    return src.cluster_stacks(timeout_s) or {}


def _capture_collectives(src, timeout_s: float, ctx: dict):
    return src.collective_health(timeout_s) or {}


def _capture_flight_records(src, timeout_s: float, ctx: dict):
    return src.flight_records(timeout_s) or {}


def _capture_serve(src, timeout_s: float, ctx: dict):
    from ..state import api as state_api
    rows = ctx.get("metrics_rows")
    if rows is None:    # metrics section failed: one fallback fetch
        rows = state_api.shape_metrics(src.state_query("metrics") or {})
    return state_api.serve_health_from_rows(rows)


def _capture_serve_requests(src, timeout_s: float, ctx: dict):
    return src.serve_requests(200) or []


def _capture_reconstruct_stats(src, timeout_s: float, ctx: dict):
    return src.state_query("reconstruct_stats") or {}


_CAPTURERS = {
    "config": _capture_config,
    "nodes": _capture_nodes,
    "resources": _capture_resources,
    "tasks": _capture_tasks,
    "actors": _capture_actors,
    "objects": _capture_objects,
    "memory": _capture_memory,
    "jobs": _capture_jobs,
    "placement_groups": _capture_placement_groups,
    "events": _capture_events,
    "lifecycle": _capture_lifecycle,
    "spans": _capture_spans,
    "metrics": _capture_metrics,
    "metrics_history": _capture_metrics_history,
    "stacks": _capture_stacks,
    "collectives": _capture_collectives,
    "flight_records": _capture_flight_records,
    "serve": _capture_serve,
    "serve_requests": _capture_serve_requests,
    "reconstruct_stats": _capture_reconstruct_stats,
}


# --------------------------------------------------------------- capture

def capture(path: str, source, reason: str = "manual",
            timeout_s: float = 2.0,
            fields: Optional[dict] = None) -> str:
    """Write one post-mortem bundle to ``path`` (a ``.tar.gz``). Every
    section is captured best-effort — a half-dead cluster yields a
    bundle with per-section error markers, never no bundle — and the
    manifest (sorted keys, sections in registry order) makes the
    schema byte-deterministic for the golden pin."""
    created = time.time()
    sections: List[dict] = []
    blobs: Dict[str, bytes] = {}
    ctx: Dict[str, Any] = {}     # shared between sections: the serve
    for name in BUNDLE_SECTIONS:     # shaper reuses the metrics fetch
        try:
            payload = _CAPTURERS[name](source, timeout_s, ctx)
            ok = True
        except Exception as e:   # noqa: BLE001 — capture is best-effort
            payload = {"capture_error": str(e)}
            ok = False
        blob = json.dumps(payload, default=str, sort_keys=True).encode()
        blobs[name] = blob
        sections.append({"name": name, "file": f"{name}.json",
                         "ok": ok, "bytes": len(blob)})
    manifest = {
        "kind": BUNDLE_KIND,
        "format_version": BUNDLE_FORMAT_VERSION,
        "reason": reason,
        "created_ts": created,
        "source": getattr(source, "kind", "unknown"),
        "sections": sections,
        **({"fields": fields} if fields else {}),
    }
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with tarfile.open(tmp, "w:gz") as tar:
        _add_member(tar, "manifest.json",
                    json.dumps(manifest, default=str,
                               sort_keys=True).encode(), created)
        for name in BUNDLE_SECTIONS:
            _add_member(tar, f"{name}.json", blobs[name], created)
    os.replace(tmp, path)
    telemetry.counter_inc(M_BUNDLES, 1.0, (("reason", reason),))
    try:
        source.emit_event({
            "message": f"debug bundle captured ({reason}): {path}",
            "path": path, "reason": reason,
            "sections_ok": sum(1 for s in sections if s["ok"]),
            "sections": len(sections),
        })
    except Exception:   # noqa: BLE001 — the bundle is already on disk
        pass
    return path


def _add_member(tar: tarfile.TarFile, name: str, blob: bytes,
                mtime: float) -> None:
    info = tarfile.TarInfo(name)
    info.size = len(blob)
    info.mtime = int(mtime)
    tar.addfile(info, io.BytesIO(blob))


_auto_captured: set = set()
_auto_lock = locksan.lock("debug.bundle")


def default_bundle_dir() -> str:
    if CONFIG.debug_bundle_dir:
        return CONFIG.debug_bundle_dir
    try:
        import ray_tpu
        session = getattr(ray_tpu, "_session_dir", None)
        if session:
            return session
    except Exception:   # noqa: BLE001 — early startup
        pass
    return tempfile.gettempdir()


def auto_capture(reason: str, node=None, fields: Optional[dict] = None,
                 background: bool = False) -> Optional[str]:
    """Terminal-failure hook: capture one bundle per (process, reason)
    when ``debug_bundle_on_failure`` is on. Uses the given node's own
    surfaces, else the process's connected client. Never raises; with
    ``background=True`` the capture runs on a daemon thread (the
    OOM-kill path must not stall the node tick) and the chosen path is
    returned immediately."""
    if not CONFIG.debug_bundle_on_failure:
        return None
    with _auto_lock:
        if reason in _auto_captured:
            return None
        _auto_captured.add(reason)
    source = None
    if node is not None:
        source = NodeSource(node)
    else:
        from . import context as _ctx
        client = _ctx.current_client
        if client is None or client._closed.is_set():
            return None
        source = ClientSource(client)
    path = os.path.join(
        default_bundle_dir(),
        f"rtpu_bundle_{reason}_{os.getpid()}_{int(time.time())}.tar.gz")

    def run() -> Optional[str]:
        try:
            capture(path, source, reason=reason, fields=fields)
            print(f"[rtpu] post-mortem debug bundle captured ({reason}): "
                  f"{path} — inspect with `rtpu autopsy {path}`",
                  file=sys.stderr)
            return path
        except Exception as e:   # noqa: BLE001 — must not mask the
            print(f"[rtpu] debug bundle capture failed ({reason}): {e}",
                  file=sys.stderr)          # original failure
            return None

    if background:
        threading.Thread(target=run, daemon=True,
                         name="rtpu-debug-bundle").start()
        return path
    return run()


# ------------------------------------------------------------------ load

def load(path: str) -> Dict[str, Any]:
    """Read a bundle back: ``{"manifest": {...}, "<section>": payload}``.
    Verifies the kind/format version so an autopsy of the wrong tar
    fails with a clear error instead of nonsense."""
    out: Dict[str, Any] = {}
    with tarfile.open(path, "r:*") as tar:
        for member in tar.getmembers():
            if not member.name.endswith(".json"):
                continue
            f = tar.extractfile(member)
            if f is None:
                continue
            try:
                payload = json.loads(f.read().decode())
            except ValueError:
                continue
            out[member.name[:-len(".json")]] = payload
    manifest = out.get("manifest") or {}
    if manifest.get("kind") != BUNDLE_KIND:
        raise ValueError(f"{path} is not a {BUNDLE_KIND} "
                         "(missing/foreign manifest)")
    if manifest.get("format_version", 0) > BUNDLE_FORMAT_VERSION:
        raise ValueError(
            f"bundle format v{manifest.get('format_version')} is newer "
            f"than this build understands (v{BUNDLE_FORMAT_VERSION})")
    return out


# --------------------------------------------------------------- autopsy

def build_autopsy(bundle: Dict[str, Any],
                  trend_window: Optional[float] = None) -> Dict[str, Any]:
    """Rebuild the investigable surfaces from a loaded bundle — the
    doctor report (with trends), serve health (+trend), the collective
    verdicts, and the memory rollup — through the SAME pure builders
    the live CLI uses. No cluster is consulted."""
    from . import history as history_mod
    from ..state import api as state_api

    mem = bundle.get("memory") or {}
    hist_dump = bundle.get("metrics_history") or {}
    window = trend_window or state_api._DOCTOR_TREND_WINDOW_S
    hist_q = history_mod.query_dump(hist_dump, window=window)
    data = {
        "nodes": bundle.get("nodes") or [],
        "resources": bundle.get("resources") or {},
        "tasks": bundle.get("tasks") or [],
        "actors": bundle.get("actors") or [],
        "events": (bundle.get("events") or {}).get("rows") or [],
        "collectives": bundle.get("collectives") or {},
        "memory": {"objects": mem.get("objects") or [],
                   "leaks": mem.get("leaks") or []},
        "metrics": bundle.get("metrics") or [],
        "history": hist_q,
    }
    doctor = state_api.build_health_report(data)
    serve = bundle.get("serve") or state_api.serve_health_from_rows(
        data["metrics"])
    serve["trend"] = state_api.shape_serve_trends(hist_q)
    memory_summary = state_api.summarize_memory_rows(
        mem.get("objects") or [])
    memory_summary["leaks"] = mem.get("leaks") or []
    memory_summary["stores"] = mem.get("stores") or {}
    manifest = bundle.get("manifest") or {}
    return {
        "manifest": manifest,
        # what killed the session, verbatim from the capture site (the
        # dead-rank verdict of an exhausted reform, the OOM victim):
        # the collective op itself is already retired by capture time,
        # so the trigger carries the verdict the survivors saw
        "trigger": {"reason": manifest.get("reason"),
                    **(manifest.get("fields") or {})},
        "doctor": doctor,
        "trends": doctor.get("trends") or [],
        "serve": serve,
        "collectives": bundle.get("collectives") or {},
        "flight_records": bundle.get("flight_records") or {},
        "memory": memory_summary,
        "history": hist_q,
        "events_stats": (bundle.get("events") or {}).get("stats") or {},
    }
