"""Durable workflows: run a task DAG with per-step checkpointing and
crash-resume.

Reference analogue: ``python/ray/workflow/`` (``api.py`` run/resume/
get_output/list_all, ``workflow_executor.py``, ``workflow_storage.py``).
Same core contract: each step's result is checkpointed to storage as it
completes; a re-run (or ``resume`` after a crash) skips every
checkpointed step and recomputes only what's missing; the DAG and its
inputs are persisted so resume works from a fresh driver process.

Scope notes (explicit descopes, mirroring the reference's deprecations):
virtual actors and workflow events are not implemented; actor nodes
(``ClassNode``/``ClassMethodNode``) are rejected in workflows because
actor state cannot be checkpointed durably — use task nodes.
"""

from __future__ import annotations

import json
import os
import pickle
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

from .._private import locksan
from .._private import serialization as ser
from ..dag import (ClassMethodNode, ClassNode, DAGInputData, DAGNode,
                   FunctionNode, InputAttributeNode, InputNode,
                   MultiOutputNode)

# statuses (reference: workflow_state WorkflowStatus)
RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"
RESUMABLE = "RESUMABLE"

_storage_dir: Optional[str] = None
_lock = locksan.lock("workflow.registry")


def init(storage: Optional[str] = None) -> None:
    """Set the workflow storage root (default:
    ``$RTPU_WORKFLOW_STORAGE`` or ``~/rtpu_workflows``)."""
    global _storage_dir
    _storage_dir = storage


def _storage() -> str:
    return (_storage_dir or os.environ.get("RTPU_WORKFLOW_STORAGE")
            or os.path.expanduser("~/rtpu_workflows"))


class _WorkflowStorage:
    """Filesystem layout: <root>/<workflow_id>/{state.json, dag.pkl,
    input.pkl, output.pkl, steps/<step_id>.pkl} (reference:
    ``workflow_storage.py`` key scheme)."""

    def __init__(self, workflow_id: str):
        self.dir = os.path.join(_storage(), workflow_id)
        self.steps_dir = os.path.join(self.dir, "steps")

    def create(self, dag: DAGNode, args: tuple, kwargs: dict) -> None:
        os.makedirs(self.steps_dir, exist_ok=True)
        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            f.write(ser.dumps_function(dag))
        with open(os.path.join(self.dir, "input.pkl"), "wb") as f:
            f.write(ser.dumps_function((args, kwargs)))
        with open(os.path.join(self.dir, "plan.json"), "w") as f:
            json.dump(_plan_fingerprint(dag, args, kwargs), f)
        self.set_status(RUNNING)

    def check_same_plan(self, dag: DAGNode, args: tuple,
                        kwargs: dict) -> None:
        try:
            with open(os.path.join(self.dir, "plan.json")) as f:
                stored = json.load(f)
        except (OSError, ValueError):
            return
        current = _plan_fingerprint(dag, args, kwargs)
        if stored.get("hash_v") != current.get("hash_v"):
            # encoding changed between releases: only the structural
            # fields are comparable
            stored = {k: v for k, v in stored.items()
                      if k in ("steps", "edges")}
            current = {k: v for k, v in current.items()
                       if k in ("steps", "edges")}
        if stored != current:
            raise ValueError(
                "workflow id already exists with a DIFFERENT dag or "
                "inputs; reusing its checkpoints would return results "
                "of the old computation. Use a new workflow_id, "
                "resume() the old one, or delete() it first.")

    def load_dag(self):
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            dag = ser.loads_function(f.read())
        with open(os.path.join(self.dir, "input.pkl"), "rb") as f:
            args, kwargs = ser.loads_function(f.read())
        return dag, args, kwargs

    def set_status(self, status: str, error: str = "") -> None:
        state = {"status": status, "updated_at": time.time()}
        if error:
            state["error"] = error
        tmp = os.path.join(self.dir, "state.json.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, os.path.join(self.dir, "state.json"))

    def status(self) -> Optional[str]:
        try:
            with open(os.path.join(self.dir, "state.json")) as f:
                return json.load(f)["status"]
        except (OSError, KeyError, ValueError):
            return None

    def save_step(self, step_id: str, value: Any) -> None:
        tmp = os.path.join(self.steps_dir, step_id + ".tmp")
        with open(tmp, "wb") as f:
            pickle.dump(value, f, protocol=5)
        os.replace(tmp, os.path.join(self.steps_dir, step_id + ".pkl"))

    def load_step(self, step_id: str):
        path = os.path.join(self.steps_dir, step_id + ".pkl")
        if not os.path.exists(path):
            return False, None
        with open(path, "rb") as f:
            return True, pickle.load(f)

    def save_output(self, value: Any) -> None:
        self.save_step("__output__", value)
        self.set_status(SUCCESSFUL)

    def load_output(self):
        return self.load_step("__output__")

    def exists(self) -> bool:
        return os.path.isdir(self.dir)


def _step_ids(dag: DAGNode) -> Dict[int, str]:
    """Deterministic step ids: post-order index + node label. The walk
    order depends only on DAG structure, so ids are stable across the
    pickle/unpickle boundary resume crosses."""
    ids = {}
    for idx, node in enumerate(dag.walk()):
        if isinstance(node, (ClassNode, ClassMethodNode)):
            raise ValueError(
                "workflows cannot contain actor nodes (actor state is "
                "not durable); use task nodes")
        if isinstance(node, FunctionNode):
            if getattr(node._remote_fn, "_handle", None) is not None:
                # a live-handle ActorMethod bound via .bind(): the pickled
                # handle in dag.pkl would point at a dead actor on resume
                raise ValueError(
                    "workflows cannot contain live actor-method nodes "
                    "(the actor will not exist at resume time); use "
                    "task nodes")
            label = getattr(node._remote_fn, "_name", "fn")
            ids[id(node)] = f"{idx:04d}-{label}"
    return ids


def _plan_fingerprint(dag: DAGNode, args: tuple, kwargs: dict) -> dict:
    """Structural fingerprint persisted at creation so a later
    ``run(other_dag, workflow_id=same)`` is rejected instead of silently
    served stale checkpoints: step ids, dependency edges, and a hash of
    the constant bound args + workflow inputs."""
    import hashlib

    ids = _step_ids(dag)
    nodes = list(dag.walk())
    index = {id(n): i for i, n in enumerate(nodes)}
    # JSON-native shapes only (the stored copy round-trips through json)
    edges = sorted([index[id(c)], index[id(n)]]
                   for n in nodes for c in n._children())
    # Hash a canonical value encoding, not repr(): reprs truncate large
    # arrays (different inputs would collide) and embed object addresses
    # (identical re-runs would spuriously differ). Raw pickle bytes are
    # also not enough — set iteration order varies across interpreter
    # hash seeds — so containers are canonicalized first.
    consts = [([a for a in n._bound_args if not isinstance(a, DAGNode)],
               {k: v for k, v in sorted(n._bound_kwargs.items())
                if not isinstance(v, DAGNode)})
              for n in nodes]
    h = hashlib.sha256()
    _stable_update(h, (consts, args, kwargs))
    # "hash_v" versions the encoding: plans checkpointed under an older
    # scheme skip the args comparison instead of spuriously rejecting an
    # identical re-run (structure — steps/edges — is still compared)
    return {"steps": sorted(ids.values()), "edges": edges,
            "args_hash": h.hexdigest(), "hash_v": 2}


def _stable_update(h, obj) -> None:
    """Feed ``obj`` into hash ``h`` as a canonical, process-stable byte
    encoding. Containers are walked with type tags; unordered containers
    are sorted by their members' canonical digests (set pickle bytes
    depend on the interpreter hash seed); arrays hash their raw buffer;
    anything else falls back to its pickled bytes."""
    import hashlib
    import numpy as _np

    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        h.update(f"{type(obj).__name__}:{obj!r};".encode())
    elif isinstance(obj, (list, tuple)):
        h.update(f"{type(obj).__name__}[{len(obj)}](".encode())
        for item in obj:
            _stable_update(h, item)
        h.update(b")")
    elif isinstance(obj, dict):
        h.update(f"dict[{len(obj)}](".encode())
        for key, sub in sorted(obj.items(),
                               key=lambda kv: _stable_digest(kv[0])):
            _stable_update(h, key)
            _stable_update(h, sub)
        h.update(b")")
    elif isinstance(obj, (set, frozenset)):
        h.update(f"{type(obj).__name__}[{len(obj)}](".encode())
        for d in sorted(_stable_digest(item) for item in obj):
            h.update(d)
        h.update(b")")
    elif isinstance(obj, _np.ndarray):
        if obj.dtype == object:
            # object arrays' raw buffer is PyObject pointers — hash the
            # elements by value instead
            h.update(f"ndarray:object:{obj.shape}(".encode())
            for item in obj.ravel():
                _stable_update(h, item)
            h.update(b")")
        else:
            arr = _np.ascontiguousarray(obj)
            h.update(f"ndarray:{arr.dtype}:{arr.shape};".encode())
            h.update(arr.tobytes())
    else:
        h.update(b"pickle:")
        h.update(ser.dumps_function(obj))


def _stable_digest(obj) -> bytes:
    import hashlib
    h = hashlib.sha256()
    _stable_update(h, obj)
    return h.digest()


def _execute_durable(wf: _WorkflowStorage, dag: DAGNode, args: tuple,
                     kwargs: dict) -> Any:
    """Wave-scheduled execution: every FunctionNode whose deps are
    resolved is submitted concurrently; results are checkpointed as they
    arrive (parallel branches stay parallel, like the reference's
    executor)."""
    import ray_tpu

    ids = _step_ids(dag)
    nodes = list(dag.walk())
    values: Dict[int, Any] = {}
    in_flight: Dict[Any, DAGNode] = {}            # ref -> node

    def deps_of(node: DAGNode) -> List[DAGNode]:
        return node._children()

    def resolve_inline(node: DAGNode):
        """Non-task nodes evaluate on the driver from resolved deps."""
        if isinstance(node, InputNode):
            if not args and not kwargs:
                raise ValueError("workflow DAG has an InputNode but no "
                                 "input args were given")
            if len(args) == 1 and not kwargs:
                return args[0]
            return DAGInputData(args, kwargs)
        if isinstance(node, InputAttributeNode):
            base = values[id(node._bound_args[0])]
            return (base[node._key] if node._kind == "item"
                    else getattr(base, node._key))
        if isinstance(node, MultiOutputNode):
            return [values[id(a)] if isinstance(a, DAGNode) else a
                    for a in node._bound_args]
        raise TypeError(f"unsupported workflow node {type(node)}")

    def ready(node) -> bool:
        return all(id(d) in values for d in deps_of(node))

    def submit_ready():
        for node in nodes:
            if id(node) in values or node in in_flight.values():
                continue
            if not ready(node):
                continue
            if isinstance(node, FunctionNode):
                done, val = wf.load_step(ids[id(node)])
                if done:
                    values[id(node)] = val
                    continue
                call_args = [values[id(a)] if isinstance(a, DAGNode) else a
                             for a in node._bound_args]
                call_kwargs = {
                    k: values[id(v)] if isinstance(v, DAGNode) else v
                    for k, v in node._bound_kwargs.items()}
                ref = node._remote_fn.remote(*call_args, **call_kwargs)
                in_flight[ref] = node
            else:
                values[id(node)] = resolve_inline(node)

    submit_ready()
    while id(dag) not in values:
        if not in_flight:
            submit_ready()
            if not in_flight and id(dag) not in values:
                raise RuntimeError("workflow made no progress "
                                   "(cycle or unresolvable node)")
            continue
        done_refs, _ = ray_tpu.wait(list(in_flight), num_returns=1)
        ref = done_refs[0]
        node = in_flight.pop(ref)
        val = ray_tpu.get(ref)
        wf.save_step(ids[id(node)], val)
        values[id(node)] = val
        submit_ready()
    return values[id(dag)]


def run(dag: DAGNode, *dag_args, workflow_id: Optional[str] = None,
        **dag_kwargs) -> Any:
    """Execute a DAG durably; returns the final output. A re-run with
    the same ``workflow_id`` skips checkpointed steps (idempotent)."""
    if workflow_id is None:
        workflow_id = f"wf-{int(time.time() * 1000):x}-{os.getpid():x}"
    wf = _WorkflowStorage(workflow_id)
    with _lock:
        if wf.exists():
            wf.check_same_plan(dag, dag_args, dag_kwargs)
            has_out, out = wf.load_output()
            if has_out:
                return out
            wf.set_status(RUNNING)       # an active retry is not FAILED
        else:
            wf.create(dag, dag_args, dag_kwargs)
    try:
        out = _execute_durable(wf, dag, dag_args, dag_kwargs)
    except Exception as e:
        wf.set_status(FAILED, error=repr(e))
        raise
    wf.save_output(out)
    return out


def run_async(dag: DAGNode, *dag_args,
              workflow_id: Optional[str] = None, **dag_kwargs) -> Future:
    """``run`` on a background thread; returns a Future."""
    fut: Future = Future()

    def target():
        try:
            fut.set_result(run(dag, *dag_args, workflow_id=workflow_id,
                               **dag_kwargs))
        except BaseException as e:  # noqa: BLE001 - delivered via Future
            fut.set_exception(e)

    threading.Thread(target=target, daemon=True,
                     name=f"rtpu-workflow-{workflow_id}").start()
    return fut


def resume(workflow_id: str) -> Any:
    """Re-drive a crashed/failed workflow from its checkpoints."""
    wf = _WorkflowStorage(workflow_id)
    if not wf.exists():
        raise ValueError(f"no workflow {workflow_id!r} in {_storage()}")
    has_out, out = wf.load_output()
    if has_out:
        return out
    dag, args, kwargs = wf.load_dag()
    wf.set_status(RUNNING)
    try:
        out = _execute_durable(wf, dag, args, kwargs)
    except Exception as e:
        wf.set_status(FAILED, error=repr(e))
        raise
    wf.save_output(out)
    return out


def get_status(workflow_id: str) -> Optional[str]:
    st = _WorkflowStorage(workflow_id).status()
    if st == RUNNING:
        # a RUNNING state with no live driver is a crashed run; we cannot
        # detect liveness across processes cheaply, so report RESUMABLE
        # (resume of a genuinely-running workflow is a user error, as in
        # the reference)
        return RESUMABLE
    return st


def get_output(workflow_id: str) -> Any:
    has_out, out = _WorkflowStorage(workflow_id).load_output()
    if not has_out:
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status={get_status(workflow_id)})")
    return out


def list_all() -> List[tuple]:
    root = _storage()
    out = []
    if os.path.isdir(root):
        for wid in sorted(os.listdir(root)):
            st = _WorkflowStorage(wid).status()
            if st is not None:
                out.append((wid, RESUMABLE if st == RUNNING else st))
    return out


def delete(workflow_id: str) -> None:
    import shutil
    wf = _WorkflowStorage(workflow_id)
    if wf.exists():
        shutil.rmtree(wf.dir)
