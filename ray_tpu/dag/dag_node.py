"""Lazy task/actor DAGs: ``.bind()`` builds the graph, ``.execute()``
submits it.

Reference analogue: ``python/ray/dag/dag_node.py:23`` (DAGNode),
``function_node.py`` / ``class_node.py`` / ``input_node.py`` /
``output_node.py``. Same authoring surface — ``fn.bind(...)``,
``Actor.bind(...)``, ``node.method.bind(...)``, ``InputNode``,
``MultiOutputNode`` — with one execution semantic: every bound task is
submitted with its upstream results passed as ``ObjectRef``s, so the
scheduler pipelines the whole graph without materializing intermediates
on the driver (the data plane stays in the object store / device mesh).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

_MISSING = object()


class DAGNode:
    """Base: a lazily-bound computation with DAG-node arguments."""

    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- traversal -----------------------------------------------------
    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def execute(self, *input_args, **input_kwargs) -> Any:
        """Submit the whole graph; returns the ObjectRef(s) of this node
        (a list for MultiOutputNode). Diamond dependencies submit once."""
        ctx = _ExecutionContext(input_args, input_kwargs)
        return self._resolve(ctx)

    def _resolve(self, ctx: "_ExecutionContext") -> Any:
        memo = ctx.memo
        if id(self) in memo:
            return memo[id(self)]
        result = self._execute_impl(ctx)
        memo[id(self)] = result
        return result

    def _resolve_args(self, ctx) -> Tuple[list, dict]:
        args = [a._resolve(ctx) if isinstance(a, DAGNode) else a
                for a in self._bound_args]
        kwargs = {k: (v._resolve(ctx) if isinstance(v, DAGNode) else v)
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_impl(self, ctx) -> Any:
        raise NotImplementedError

    # -- introspection (used by workflow's planner) --------------------
    def walk(self):
        """Yield every node in the graph (post-order, deduped)."""
        seen = set()

        def rec(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for c in node._children():
                yield from rec(c)
            yield node

        yield from rec(self)


class _ExecutionContext:
    def __init__(self, input_args: tuple, input_kwargs: dict):
        self.input_args = input_args
        self.input_kwargs = input_kwargs
        self.memo: Dict[int, Any] = {}


class FunctionNode(DAGNode):
    """``remote_fn.bind(*args)`` — executes as one task submission."""

    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, ctx):
        args, kwargs = self._resolve_args(ctx)
        return self._remote_fn.remote(*args, **kwargs)

    def __repr__(self):
        return f"FunctionNode({self._remote_fn._name})"


class DAGInputData:
    """The full ``execute(*args, **kwargs)`` payload when more than one
    value was passed (reference: ``dag/input_node.py`` DAGInputData).
    ``[int]`` selects positionals, ``[str]``/attribute selects kwargs."""

    def __init__(self, args: tuple, kwargs: dict):
        self.args = args
        self.kwargs = kwargs

    def __getitem__(self, key):
        if isinstance(key, int):
            return self.args[key]
        return self.kwargs[key]

    def __getattr__(self, name):
        try:
            return self.__dict__["kwargs"][name]
        except KeyError:
            raise AttributeError(name) from None


class InputNode(DAGNode):
    """Placeholder for ``execute()``'s arguments.

    ``with InputNode() as inp:`` matches the reference's authoring
    idiom. With a single positional argument ``inp`` IS that value;
    otherwise it is a :class:`DAGInputData` and ``inp[i]`` /
    ``inp.field`` select into positionals / keywords.
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_impl(self, ctx):
        if not ctx.input_args and not ctx.input_kwargs:
            raise ValueError("DAG contains an InputNode but execute() "
                             "was called with no arguments")
        if len(ctx.input_args) == 1 and not ctx.input_kwargs:
            return ctx.input_args[0]
        return DAGInputData(ctx.input_args, ctx.input_kwargs)

    def __getitem__(self, key):
        return InputAttributeNode(self, key, kind="item")

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return InputAttributeNode(self, name, kind="attr")


class InputAttributeNode(DAGNode):
    """``inp[key]`` / ``inp.attr`` — selects into the execute() input."""

    def __init__(self, parent: DAGNode, key, kind: str):
        super().__init__((parent,), {})
        self._key = key
        self._kind = kind

    def _execute_impl(self, ctx):
        base = self._bound_args[0]._resolve(ctx)
        if self._kind == "item":
            return base[self._key]
        return getattr(base, self._key)


class ClassNode(DAGNode):
    """``ActorClass.bind(*ctor_args)`` — instantiated at execute()."""

    def __init__(self, actor_cls, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls

    def _execute_impl(self, ctx):
        args, kwargs = self._resolve_args(ctx)
        return self._actor_cls.remote(*args, **kwargs)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodProxy(self, name)


class _ClassMethodProxy:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name,
                               args, kwargs)


class ClassMethodNode(DAGNode):
    """``class_node.method.bind(*args)`` — an actor call in the graph.

    The owning actor is created once per ``execute()`` (memoized via the
    ClassNode), so chained method nodes hit the same instance.
    """

    def __init__(self, class_node: ClassNode, method_name: str,
                 args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _children(self):
        return [self._class_node] + super()._children()

    def _execute_impl(self, ctx):
        handle = self._class_node._resolve(ctx)
        args, kwargs = self._resolve_args(ctx)
        return getattr(handle, self._method_name).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Bundle several terminal nodes; ``execute()`` returns their refs
    as a list (reference: ``output_node.py``)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _execute_impl(self, ctx):
        return [a._resolve(ctx) if isinstance(a, DAGNode) else a
                for a in self._bound_args]
