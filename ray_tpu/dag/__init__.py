"""Lazy DAG authoring API (reference: ``python/ray/dag/``)."""

from .dag_node import (ClassMethodNode, ClassNode, DAGInputData, DAGNode,
                       FunctionNode, InputAttributeNode, InputNode,
                       MultiOutputNode)

__all__ = ["DAGNode", "DAGInputData", "FunctionNode", "ClassNode",
           "ClassMethodNode", "InputNode", "InputAttributeNode",
           "MultiOutputNode"]
