"""File-based datasources: one abstraction, many formats.

Reference analogue: ``python/ray/data/datasource/file_based_datasource.py``
(+ the per-format datasources under ``python/ray/data/datasource/``).
Design differs: a datasource here is a factory of per-file block
GENERATORS — each file is read by one streaming task
(``num_returns="streaming"``) that yields bounded-row blocks as it goes,
so a single huge file never materializes in the reading worker and the
consumer sees the first block while the read still runs.
"""

from __future__ import annotations

import glob as _glob

import os
import struct
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from .block import Block, block_from_rows

DEFAULT_ROWS_PER_BLOCK = 4096


def expand_paths(paths, extension=None) -> List[str]:
    """Files / dirs / globs → sorted file list (reference:
    ``file_based_datasource.py`` path expansion). ``extension`` may be
    one suffix, a tuple of suffixes, or None (match everything)."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    exts = ((extension,) if isinstance(extension, str) else extension)
    out: List[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            pats = [f"*{e}" for e in exts] if exts else ["*"]
            hits = set()
            for pat in pats:
                hits.update(_glob.glob(os.path.join(p, pat)))
            out.extend(sorted(hits))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


class FileBasedDatasource:
    """Base: subclasses implement ``read_file(path) -> Iterator[Block]``.

    ``sources()`` returns one generator-callable per file, ready for
    ``Dataset(sources=..., source_streaming=True)``.
    """

    extension: Optional[str] = None

    def __init__(self, paths, *, rows_per_block: int = DEFAULT_ROWS_PER_BLOCK,
                 **options: Any):
        self.paths = expand_paths(paths, self.extension)
        self.rows_per_block = rows_per_block
        self.options = options

    def read_file(self, path: str) -> Iterator[Block]:
        raise NotImplementedError

    def sources(self) -> List[Callable[[], Iterator[Block]]]:
        def make(path: str):
            def gen() -> Iterator[Block]:
                yield from self.read_file(path)
            return gen
        return [make(p) for p in self.paths]

    # ------------------------------------------------------------ helpers
    def _batched_rows(self, rows: Iterator[Dict[str, Any]]
                      ) -> Iterator[Block]:
        buf: List[Dict[str, Any]] = []
        for row in rows:
            buf.append(row)
            if len(buf) >= self.rows_per_block:
                yield block_from_rows(buf)
                buf = []
        if buf:
            yield block_from_rows(buf)


class CSVDatasource(FileBasedDatasource):
    extension = ".csv"

    def read_file(self, path: str) -> Iterator[Block]:
        import csv

        # Dtypes are decided ONCE PER FILE (cheap text pre-pass), then
        # applied to every block: per-block inference would give one
        # column different dtypes in different blocks (int64 in an
        # all-numeric block, object where an "n/a" appears), and
        # block_concat would silently promote the numeric rows to
        # strings.
        dtypes = _infer_csv_dtypes(path)
        with open(path, newline="") as f:
            for blk in self._batched_rows(csv.DictReader(f)):
                yield {k: (v.astype(dtypes[k])
                           if dtypes.get(k) is not None else v)
                       for k, v in blk.items()}


def _infer_csv_dtypes(path: str) -> Dict[str, Any]:
    """Per-column dtype for a whole CSV file: int64 if every cell parses
    as int, else float64 if every cell parses as float, else None
    (keep strings)."""
    import csv

    with open(path, newline="") as f:
        reader = csv.DictReader(f)
        state: Dict[str, Any] = {k: np.int64
                                 for k in (reader.fieldnames or [])}
        for row in reader:
            undecided = False
            for k, dt in state.items():
                if dt is None:
                    continue
                undecided = True
                val = row.get(k)
                try:
                    if not (-2**63 <= int(val) < 2**63):
                        raise OverflowError  # would not fit int64
                except (TypeError, ValueError, OverflowError):
                    try:
                        float(val)
                        state[k] = np.float64
                    except (TypeError, ValueError):
                        state[k] = None
            if not undecided:
                break
    return state


class JSONDatasource(FileBasedDatasource):
    """JSONL by default; ``lines=False`` reads one JSON array per file."""

    extension = ".json"

    def __init__(self, paths, **kw):
        if kw.get("lines", True):
            self.extension = ".jsonl"      # instance attr: dir expansion
        super().__init__(paths, **kw)

    def read_file(self, path: str) -> Iterator[Block]:
        import json

        lines = self.options.get("lines", True)
        with open(path) as f:
            if lines:
                rows = (json.loads(ln) for ln in f if ln.strip())
                yield from self._batched_rows(rows)
            else:
                data = json.load(f)
                rows = data if isinstance(data, list) else [data]
                yield from self._batched_rows(iter(rows))


class ParquetDatasource(FileBasedDatasource):
    extension = ".parquet"

    def read_file(self, path: str) -> Iterator[Block]:
        try:
            import pyarrow.parquet as pq
        except ImportError as e:
            raise ImportError(
                "read_parquet requires pyarrow, which is not available "
                "in this environment") from e
        pf = pq.ParquetFile(path)
        columns = self.options.get("columns")
        # row-group granularity: a 100-row-group file streams 100 blocks
        for i in range(pf.num_row_groups):
            table = pf.read_row_group(i, columns=columns)
            yield {name: np.asarray(col) for name, col in
                   zip(table.column_names, table.to_pydict().values())}


class TextDatasource(FileBasedDatasource):
    """One row per line: {"text": <str>} (reference:
    ``datasource/text_datasource.py``)."""

    extension = ".txt"

    def read_file(self, path: str) -> Iterator[Block]:
        encoding = self.options.get("encoding", "utf-8")
        drop_empty = self.options.get("drop_empty_lines", True)
        with open(path, encoding=encoding, errors="replace") as f:
            rows = ({"text": ln.rstrip("\n")} for ln in f
                    if not drop_empty or ln.strip())
            yield from self._batched_rows(rows)


class BinaryDatasource(FileBasedDatasource):
    """One row per file: {"bytes": ..., "path": ...} (reference:
    ``datasource/binary_datasource.py``)."""

    def read_file(self, path: str) -> Iterator[Block]:
        with open(path, "rb") as f:
            data = f.read()
        yield {"bytes": np.array([data], dtype=object),
               "path": np.array([path], dtype=object)}


class NumpyDatasource(FileBasedDatasource):
    """.npy (one array -> {"data": rows}) and .npz (one column per
    entry) (reference: ``datasource/numpy_datasource.py``)."""

    extension = (".npy", ".npz")

    def read_file(self, path: str) -> Iterator[Block]:
        if path.endswith(".npz"):
            with np.load(path) as z:
                yield {name: z[name] for name in z.files}
            return
        arr = np.load(path)
        if arr.ndim == 0:
            arr = arr[None]
        n = self.rows_per_block
        for lo in range(0, len(arr), n):
            yield {"data": arr[lo:lo + n]}


class ImageDatasource(FileBasedDatasource):
    """Rows {"image": HWC uint8, "path": str} via PIL (gated — PIL is an
    optional dependency here, like the reference's imageio gate)."""

    def read_file(self, path: str) -> Iterator[Block]:
        try:
            from PIL import Image
        except ImportError as e:
            raise ImportError(
                "read_images requires pillow, which is not available in "
                "this environment") from e
        size = self.options.get("size")
        img = Image.open(path)
        if size is not None:
            img = img.resize(size)
        mode = self.options.get("mode")
        if mode is not None:
            img = img.convert(mode)
        yield {"image": np.asarray(img)[None],
               "path": np.array([path], dtype=object)}


# --------------------------------------------------------------- tfrecord

class TFRecordDatasource(FileBasedDatasource):
    """TFRecord files of ``tf.train.Example`` protos WITHOUT a tensorflow
    dependency: the record framing (u64 length + masked-crc framing) and
    the Example/Features/Feature proto wire format are parsed directly
    (reference capability: ``datasource/tfrecords_datasource.py``)."""

    extension = ".tfrecord"

    def read_file(self, path: str) -> Iterator[Block]:
        def rows():
            with open(path, "rb") as f:
                while True:
                    header = f.read(8)
                    if len(header) < 8:
                        return
                    (length,) = struct.unpack("<Q", header)
                    f.read(4)                      # length crc (unchecked)
                    payload = f.read(length)
                    if len(payload) < length:
                        raise ValueError(f"truncated tfrecord in {path}")
                    f.read(4)                      # data crc (unchecked)
                    yield _parse_example(payload)

        yield from self._batched_rows(rows())


def _read_varint(buf: memoryview, pos: int):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: memoryview):
    """(field_number, wire_type, value) over a proto message. Supports
    varint (0), 64-bit (1), length-delimited (2), 32-bit (5)."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = _read_varint(buf, pos)
        elif wt == 1:
            val = bytes(buf[pos:pos + 8])
            pos += 8
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            val = bytes(buf[pos:pos + 4])
            pos += 4
        else:
            raise ValueError(f"unsupported proto wire type {wt}")
        yield field, wt, val


def _parse_feature(buf: memoryview):
    """tf.train.Feature: oneof bytes_list=1 / float_list=2 / int64_list=3."""
    for field, _, val in _iter_fields(buf):
        if field == 1:       # BytesList { repeated bytes value = 1 }
            return [bytes(v) for f, _, v in _iter_fields(val) if f == 1]
        if field == 2:       # FloatList { repeated float value = 1 [packed] }
            out: List[float] = []
            for f, wt, v in _iter_fields(val):
                if f != 1:
                    continue
                if wt == 2:  # packed
                    out.extend(struct.unpack(f"<{len(v) // 4}f", bytes(v)))
                else:
                    out.append(struct.unpack("<f", v)[0])
            return out
        if field == 3:       # Int64List { repeated int64 value = 1 [packed] }
            out = []
            for f, wt, v in _iter_fields(val):
                if f != 1:
                    continue
                if wt == 2:
                    pos = 0
                    while pos < len(v):
                        x, pos = _read_varint(v, pos)
                        out.append(_to_signed64(x))
                else:
                    out.append(_to_signed64(v))
            return out
    return []


def _to_signed64(x: int) -> int:
    return x - (1 << 64) if x >= (1 << 63) else x


def _parse_example(payload: bytes) -> Dict[str, Any]:
    """tf.train.Example { Features features = 1 };
    Features { map<string, Feature> feature = 1 }."""
    row: Dict[str, Any] = {}
    for field, _, val in _iter_fields(memoryview(payload)):
        if field != 1:
            continue
        for f2, _, entry in _iter_fields(val):
            if f2 != 1:
                continue
            key = None
            feature = None
            for f3, _, v3 in _iter_fields(entry):
                if f3 == 1:
                    key = bytes(v3).decode("utf-8")
                elif f3 == 2:
                    feature = _parse_feature(v3)
            if key is not None:
                vals = feature or []
                row[key] = vals[0] if len(vals) == 1 else vals
    return row


def write_tfrecords(path: str, rows: Sequence[Dict[str, Any]]) -> None:
    """Minimal writer (tests + export parity): encodes each row as a
    tf.train.Example record with the standard framing."""
    def varint(x: int) -> bytes:
        out = b""
        while True:
            b7 = x & 0x7F
            x >>= 7
            if x:
                out += bytes([b7 | 0x80])
            else:
                return out + bytes([b7])

    def ld(field: int, payload: bytes) -> bytes:
        return varint((field << 3) | 2) + varint(len(payload)) + payload

    def feature(value) -> bytes:
        if isinstance(value, (bytes, str)):
            vb = value.encode() if isinstance(value, str) else value
            return ld(1, ld(1, vb))
        if isinstance(value, (list, tuple, np.ndarray)):
            vals = list(value)
        else:
            vals = [value]
        if all(isinstance(v, (int, np.integer)) for v in vals):
            packed = b"".join(varint(v & ((1 << 64) - 1)) for v in vals)
            return ld(3, ld(1, packed))
        packed = struct.pack(f"<{len(vals)}f", *[float(v) for v in vals])
        return ld(2, ld(1, packed))

    def example(row: Dict[str, Any]) -> bytes:
        entries = b""
        for k, v in row.items():
            entry = ld(1, k.encode()) + ld(2, feature(v))
            entries += ld(1, entry)
        return ld(1, entries)

    def masked_crc(data: bytes) -> int:
        crc = _crc32c(data)
        return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF

    with open(path, "wb") as f:
        for row in rows:
            payload = example(row)
            f.write(struct.pack("<Q", len(payload)))
            f.write(struct.pack("<I", masked_crc(struct.pack(
                "<Q", len(payload)))))
            f.write(payload)
            f.write(struct.pack("<I", masked_crc(payload)))


_CRC_TABLE: Optional[List[int]] = None


def _crc32c(data: bytes) -> int:
    """Castagnoli CRC32 (table-driven); stdlib zlib.crc32 uses the wrong
    polynomial for tfrecord framing."""
    global _CRC_TABLE
    if _CRC_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF
