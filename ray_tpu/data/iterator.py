"""Streaming dataset shards for distributed ingest.

Reference analogue: ``python/ray/train/_internal/data_config.py`` +
``DataIterator`` (``python/ray/data/iterator.py``): a Dataset is split
into N live streams, one consumed by each training worker while the
read/transform pipeline keeps running on the cluster.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from .. import get
from .block import Block, block_concat, block_num_rows, block_slice


class DataIterator:
    """One worker's shard of a streaming split: block refs arrive
    through a bounded queue (backpressure: the driver-side feeder stalls
    when consumers lag). Picklable — pass into remote workers."""

    def __init__(self, queue):
        self._queue = queue

    # ------------------------------------------------------------ blocks
    def iter_block_refs(self) -> Iterator[Any]:
        while True:
            item = self._queue.get(block=True, timeout=None)
            if item is None:
                return
            if isinstance(item, tuple) and item[0] == "__stream_error__":
                # the pipeline died upstream: surface it instead of
                # hanging the consumer on a stream that will never end
                raise RuntimeError(
                    f"dataset stream failed upstream: {item[1]}")
            # refs ride WRAPPED in a 1-list: a bare ObjectRef queue item
            # would be auto-resolved into its value at the actor call
            # boundary (nested refs pass through as borrowed refs)
            yield item[0]

    def shutdown(self) -> None:
        """Tear down this shard's queue actor (trainer teardown between
        elastic restarts); the feeder thread exits on its next put."""
        try:
            self._queue.shutdown()
        except Exception:
            pass

    def iter_blocks(self) -> Iterator[Block]:
        for ref in self.iter_block_refs():
            yield get(ref)

    # ----------------------------------------------------------- batches
    def iter_batches(self, *, batch_size: int = 256,
                     drop_last: bool = False) -> Iterator[Block]:
        """Re-batch across block boundaries to exactly batch_size."""
        carry: Optional[Block] = None
        for blk in self.iter_blocks():
            if not blk:
                continue
            if carry:
                blk = block_concat([carry, blk])
                carry = None
            n = block_num_rows(blk)
            lo = 0
            while n - lo >= batch_size:
                yield block_slice(blk, lo, lo + batch_size)
                lo += batch_size
            if lo < n:
                carry = block_slice(blk, lo, n)
        if carry and not drop_last:
            yield carry

    def iter_device_batches(self, *, batch_size: int = 256,
                            sharding: Optional[Any] = None,
                            dtype: Optional[Any] = None
                            ) -> Iterator[Dict[str, Any]]:
        """Batches as jax Arrays, optionally placed with ``sharding``
        (e.g. the mesh's batch sharding for SPMD input). Partial final
        batches are dropped — jit'd train steps need static shapes."""
        import jax
        import jax.numpy as jnp

        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=True):
            out = {}
            for k, v in batch.items():
                arr = jnp.asarray(v, dtype=dtype) if dtype is not None \
                    else jnp.asarray(v)
                if sharding is not None:
                    arr = jax.device_put(arr, sharding)
                out[k] = arr
            yield out

    def __reduce__(self):
        return (DataIterator, (self._queue,))


def streaming_split(dataset, n: int, *,
                    queue_size: int = 4) -> List[DataIterator]:
    """Split a dataset into ``n`` concurrently-consumable streams.

    A driver-side feeder thread drives the dataset's streaming executor
    and deals block refs round-robin into n bounded queues; total
    cluster residency stays (operator windows + n*queue_size) blocks.
    Round-robin + bounded queues couple the shards' pace — which is what
    lockstep SPMD training wants (every rank steps together anyway).
    """
    from ..util.queue import Queue

    if n < 1:
        raise ValueError("streaming_split needs n >= 1")
    queues = [Queue(maxsize=queue_size) for _ in range(n)]

    def feed() -> None:
        end_item: Any = None
        try:
            for i, ref in enumerate(dataset.streaming_block_refs()):
                queues[i % n].put([ref], block=True, timeout=None)
        except Exception as e:  # noqa: BLE001 — delivered to consumers
            # the PIPELINE failed (bad file, missing optional dep, task
            # error): every consumer must see the error, not hang on a
            # stream that never ends
            end_item = ("__stream_error__", repr(e))
        from ..util.queue import Full
        # The sentinel MUST land: a consumer that is merely slow
        # (bounded queue full across a long train step) raises Full on
        # timeout — keep retrying. Round-robin over the still-pending
        # queues so one permanently-full queue (dead consumer, live
        # queue actor) can't starve the others of their sentinel. Drop
        # a queue only when its actor is gone (shutdown/teardown).
        pending = list(queues)
        while pending:
            still = []
            for q in pending:
                try:
                    q.put(end_item, block=True, timeout=2.0)
                except Full:
                    still.append(q)
                except Exception:
                    pass
            pending = still

    threading.Thread(target=feed, daemon=True,
                     name="rtpu-data-feeder").start()
    return [DataIterator(q) for q in queues]
