"""Dataset: lazy logical plan + streaming execution over block operators.

Reference: ``python/ray/data/dataset.py:178`` (API surface),
``_internal/plan.py`` (logical plan), ``_internal/execution/
streaming_executor.py:49`` (backpressure-aware streaming execution),
``_internal/execution/operators/map_operator.py:39`` (fused map tasks)
and ``operators/actor_pool_map_operator.py`` (stateful UDFs on a
reusable actor pool).

Execution model: consecutive task transforms fuse into one remote task
per block (one object-store pass per chain); a stage with
``compute=ActorPoolStrategy(...)`` becomes its own operator running on
a pool of long-lived actors (the UDF class is constructed once per
actor, then reused for every block). Operators chain as generators,
each holding a bounded in-flight window — the store's high-water mark
stays at ~sum(windows) blocks regardless of dataset size, and consumed
refs are freed by the distributed refcount as the consumer drops them.
All-to-all ops (repartition / random_shuffle) are barriers that
redistribute materialized block refs with slice/concat tasks.
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Sequence, Tuple)

import numpy as np

from .. import get, put, wait
from .._private import telemetry
from ..api import remote
from . import block as B

Block = B.Block

_DEFAULT_WINDOW = 8

M_DATA_BLOCKS = telemetry.define(
    "counter", "rtpu_data_blocks_total",
    "Blocks produced by data-plane operators, tagged by op")
M_DATA_ROWS = telemetry.define(
    "counter", "rtpu_data_block_rows_total",
    "Rows in blocks produced by data-plane operators")
M_DATA_BYTES = telemetry.define(
    "counter", "rtpu_data_block_bytes_total",
    "Bytes (numeric columns) in blocks produced by data-plane operators")


def _record_block(blk: Block, op: str) -> Block:
    tags = (("op", op),)
    telemetry.counter_inc(M_DATA_BLOCKS, 1.0, tags)
    telemetry.counter_inc(M_DATA_ROWS, float(B.block_num_rows(blk)), tags)
    nbytes = sum(v.nbytes for v in blk.values()
                 if getattr(v, "dtype", None) is not None
                 and v.dtype != object)
    if nbytes:
        telemetry.counter_inc(M_DATA_BYTES, float(nbytes), tags)
    return blk


# A stage is ("map_batches"|"map"|"filter"|"flat_map", fn, kwargs)
Stage = Tuple[str, Callable, dict]


class ActorPoolStrategy:
    """Run a stage's UDF on ``size`` long-lived actors (reference:
    ``ActorPoolStrategy`` / ``actor_pool_map_operator.py``). Use with a
    CLASS UDF whose construction is expensive (model weights, clients);
    each actor constructs it once and maps every block it receives.
    ``max_in_flight`` bounds queued blocks per actor (backpressure)."""

    def __init__(self, size: int = 2, max_in_flight: int = 2,
                 num_cpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None):
        if size < 1 or max_in_flight < 1:
            raise ValueError(
                f"ActorPoolStrategy needs size >= 1 and max_in_flight >= 1 "
                f"(got size={size}, max_in_flight={max_in_flight})")
        self.size = size
        self.max_in_flight = max_in_flight
        self.num_cpus = num_cpus
        self.resources = resources


def _apply_stages(blk: Block, stages: Sequence[Stage]) -> Block:
    for kind, fn, kw in stages:
        if kind == "map_batches":
            fmt = kw.get("batch_format", "numpy")
            out = fn(dict(blk) if fmt == "numpy" else list(B.block_rows(blk)))
            blk = B.normalize_block(out)
        elif kind == "map":
            blk = B.block_from_rows([fn(r) for r in B.block_rows(blk)])
        elif kind == "filter":
            keep = [i for i, r in enumerate(B.block_rows(blk)) if fn(r)]
            blk = B.block_take(blk, np.asarray(keep, np.int64)) if keep \
                else {k: v[:0] for k, v in blk.items()}
        elif kind == "flat_map":
            rows = list(itertools.chain.from_iterable(
                fn(r) for r in B.block_rows(blk)))
            blk = B.block_from_rows(rows)
        else:
            raise ValueError(f"unknown stage kind {kind}")
    return blk


@remote
def _run_block_task(source_fn: Optional[Callable], source_block,
                    stages: List[Stage]) -> Block:
    blk = source_fn() if source_fn is not None else source_block
    blk = B.normalize_block(blk)
    return _record_block(_apply_stages(blk, stages), "map_task")


@remote
def _count_block(blk: Block) -> int:
    return B.block_num_rows(blk)


@remote
def _meta_block(blk: Block):
    return B.block_metadata(blk)


@remote
def _run_gen_source(source_fn: Callable):
    """Streaming source: the producer yields blocks one by one and each
    leaves the task as soon as it is produced (num_returns=\"streaming\"
    — a 1000-block read never materializes 1000 blocks in the worker).
    Reference analogue: a read task streaming its output blocks through
    ObjectRefGenerator."""
    for blk in source_fn():
        yield _record_block(B.normalize_block(blk), "gen_source")


@remote
class _UDFActor:
    """One pool member: constructs the user's class once, maps blocks."""

    def __init__(self, ctor, args, kwargs, kind: str, stage_kw: dict):
        self.fn = ctor(*(args or ()), **(kwargs or {}))
        self.kind = kind
        self.stage_kw = stage_kw

    def call_block(self, blk: Block) -> Block:
        return _record_block(
            _apply_stages(blk, [(self.kind, self.fn, self.stage_kw)]),
            "actor_pool")


@remote
def _concat_blocks(*blocks: Block) -> Block:
    return B.block_concat(list(blocks))


@remote
def _slice_block(blk: Block, start: int, stop: int) -> Block:
    return B.block_slice(blk, start, stop)


@remote
def _add_const_key(blk: Block) -> Block:
    """Tag every row with one shared key so Dataset.aggregate can ride
    the groupby engine as a single-group reduction."""
    out = dict(blk)
    out["__all__"] = np.zeros(B.block_num_rows(blk), np.int8)
    return out


class Dataset:
    """Lazy; chainable; executed streaming on iteration/consumption."""

    def __init__(self,
                 sources: Optional[List[Callable[[], Block]]] = None,
                 block_refs: Optional[List[Any]] = None,
                 stages: Optional[List[Stage]] = None,
                 source_streaming: bool = False):
        # exactly one of sources (unread) / block_refs (materialized input)
        self._sources = sources
        self._block_refs = block_refs
        self._stages = stages or []
        # True: each source is a GENERATOR of blocks executed as a
        # streaming task (see _run_gen_source) rather than one block
        self._source_streaming = source_streaming

    # ------------------------------------------------------------ transforms
    def _with_stage(self, stage: Stage) -> "Dataset":
        return Dataset(self._sources, self._block_refs,
                       self._stages + [stage],
                       source_streaming=self._source_streaming)

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    compute: Optional[ActorPoolStrategy] = None,
                    fn_constructor_args: Optional[tuple] = None,
                    fn_constructor_kwargs: Optional[dict] = None,
                    **kw) -> "Dataset":
        """``fn`` is a callable (task stage) or, with
        ``compute=ActorPoolStrategy(...)``, a class whose instances are
        constructed once per pool actor and called per block."""
        return self._with_stage(("map_batches", fn, {
            "batch_format": batch_format, "compute": compute,
            "fn_constructor_args": fn_constructor_args,
            "fn_constructor_kwargs": fn_constructor_kwargs,
        }))

    def map(self, fn: Callable) -> "Dataset":
        return self._with_stage(("map", fn, {}))

    def filter(self, fn: Callable) -> "Dataset":
        return self._with_stage(("filter", fn, {}))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with_stage(("flat_map", fn, {}))

    # ------------------------------------------------------------- execution
    def _num_input_blocks(self) -> int:
        return len(self._sources if self._sources is not None
                   else self._block_refs or [])

    def _segments(self) -> List[Tuple[str, Any]]:
        """Fuse consecutive task stages; actor stages stand alone
        (reference: operator fusion + ActorPoolMapOperator)."""
        segs: List[Tuple[str, Any]] = []
        for st in self._stages:
            if st[2].get("compute") is not None:
                segs.append(("actors", st))
            elif segs and segs[-1][0] == "tasks":
                segs[-1][1].append(st)
            else:
                segs.append(("tasks", [st]))
        return segs

    @staticmethod
    def _task_operator(upstream: Iterator[Tuple[Optional[Callable], Any]],
                       stages: List[Stage],
                       window: int) -> Iterator[Any]:
        """Fused map tasks with a bounded in-flight window: at most
        ``window`` submitted-but-unconsumed blocks exist at this
        operator (backpressure; reference: MapOperator + the streaming
        executor's resource limits)."""
        in_flight: "deque" = deque()
        for src_fn, src_ref in upstream:
            if len(in_flight) >= window:
                yield in_flight.popleft()
            in_flight.append(_run_block_task.remote(src_fn, src_ref,
                                                    stages))
        while in_flight:
            yield in_flight.popleft()

    @staticmethod
    def _actor_operator(upstream: Iterator[Any],
                        stage: Stage) -> Iterator[Any]:
        """Map blocks over a pool of long-lived UDF actors; each actor
        holds at most ``max_in_flight`` queued blocks (reference:
        ``actor_pool_map_operator.py``)."""
        from .. import kill
        kind, ctor, kw = stage
        compute: ActorPoolStrategy = kw["compute"]
        stage_kw = {k: v for k, v in kw.items()
                    if k not in ("compute", "fn_constructor_args",
                                 "fn_constructor_kwargs")}
        opts: Dict[str, Any] = {}
        if compute.num_cpus is not None:
            opts["num_cpus"] = compute.num_cpus
        if compute.resources:
            opts["resources"] = compute.resources
        pool = [_UDFActor.options(**opts).remote(
            ctor, kw.get("fn_constructor_args"),
            kw.get("fn_constructor_kwargs"), kind, stage_kw)
            for _ in range(compute.size)]
        try:
            rr = itertools.cycle(pool)
            cap = compute.size * compute.max_in_flight
            in_flight: "deque" = deque()
            for ref in upstream:
                if len(in_flight) >= cap:
                    yield in_flight.popleft()
                in_flight.append(next(rr).call_block.remote(ref))
            while in_flight:
                # drain waits for completion: the finally kills the pool
                # the moment the consumer exhausts us, and a killed actor
                # fails its queued calls. Actors process FIFO, so the
                # last call per actor completing implies all earlier
                # yielded refs completed too.
                head = in_flight.popleft()
                wait([head], num_returns=1, timeout=None)
                yield head
        finally:
            for actor in pool:
                try:
                    kill(actor)
                except Exception:
                    pass

    def streaming_block_refs(self,
                             window: int = _DEFAULT_WINDOW
                             ) -> Iterator[Any]:
        """The streaming executor: chained operators, each with a
        bounded in-flight window, pulled by the consumer. Total live
        blocks stay ~sum of operator windows no matter how large the
        dataset is; refs the consumer drops are freed by refcounting."""
        if self._sources is not None and self._source_streaming:
            # streaming sources: block refs arrive one by one while the
            # producer task still runs; the generator's backpressure
            # window paces the producer against this consumer
            def gen_stream() -> Iterator[Any]:
                # all producers submitted up front so they run in
                # parallel (each paced by its own backpressure window);
                # drained in source order
                gens = [_run_gen_source.options(
                    num_returns="streaming").remote(fn)
                    for fn in self._sources]
                for gen in gens:
                    yield from gen
            stream = gen_stream()
            for seg_kind, payload in self._segments():
                if seg_kind == "tasks":
                    stream = self._task_operator(
                        ((None, ref) for ref in stream), payload, window)
                else:
                    stream = self._actor_operator(stream, payload)
            yield from stream
            return
        inputs: List[Tuple[Optional[Callable], Any]]
        if self._sources is not None:
            inputs = [(fn, None) for fn in self._sources]
        else:
            inputs = [(None, ref) for ref in (self._block_refs or [])]
        segs = self._segments()
        if not segs and self._sources is None:
            yield from (ref for _, ref in inputs)
            return
        if ((not segs or segs[0][0] != "tasks")
                and self._sources is not None):
            # reads executing under an actor-first pipeline still need a
            # source op; materialized refs feed the actor pool directly
            segs.insert(0, ("tasks", []))
        if segs and segs[0][0] == "tasks":
            stream: Iterator[Any] = self._task_operator(
                iter(inputs), segs[0][1], window)
            rest = segs[1:]
        else:
            stream = (ref for _, ref in inputs)
            rest = segs
        for seg_kind, payload in rest:
            if seg_kind == "tasks":
                stream = self._task_operator(
                    ((None, ref) for ref in stream), payload, window)
            else:
                stream = self._actor_operator(stream, payload)
        yield from stream

    def materialize(self) -> "Dataset":
        refs = list(self.streaming_block_refs())
        return Dataset(block_refs=refs)

    # ------------------------------------------------------------ all-to-all
    def repartition(self, num_blocks: int) -> "Dataset":
        """Barrier: equalize rows over num_blocks output blocks."""
        mat = self.materialize()
        refs = mat._block_refs or []
        counts = [B.block_num_rows(b) for b in get(refs)] if refs else []
        total = sum(counts)
        per = total // num_blocks
        sizes = [per + (1 if i < total % num_blocks else 0)
                 for i in range(num_blocks)]
        # assemble each output from input slices without driver transfer
        out_refs = []
        in_idx, in_off = 0, 0
        for size in sizes:
            parts = []
            need = size
            while need > 0 and in_idx < len(refs):
                avail = counts[in_idx] - in_off
                take = min(avail, need)
                if take > 0:
                    parts.append(_slice_block.remote(
                        refs[in_idx], in_off, in_off + take))
                    in_off += take
                    need -= take
                if in_off >= counts[in_idx]:
                    in_idx += 1
                    in_off = 0
            out_refs.append(_concat_blocks.remote(*parts) if len(parts) != 1
                            else parts[0])
        return Dataset(block_refs=out_refs)

    def random_shuffle(self, seed: Optional[int] = None, *,
                       merge_window: int = 8) -> "Dataset":
        """True all-to-all row shuffle through the push-based shuffle
        engine: every output block draws rows from every input block
        (reference: ``_internal/push_based_shuffle.py``)."""
        from .shuffle import random_shuffle_blocks
        refs = list(self.streaming_block_refs())
        return Dataset(block_refs=random_shuffle_blocks(
            refs, seed=seed, merge_window=merge_window))

    def sort(self, key: str, descending: bool = False, *,
             num_partitions: Optional[int] = None,
             merge_window: int = 8) -> "Dataset":
        """Distributed sort by a column (reference: ``Dataset.sort`` via
        ``planner/exchange/sort_task_spec.py``): sample → range
        partition through the shuffle engine → per-partition sort.
        Output blocks are globally ordered."""
        from .shuffle import sort_blocks
        refs = list(self.streaming_block_refs())
        return Dataset(block_refs=sort_blocks(
            refs, key, descending=descending,
            num_partitions=num_partitions, merge_window=merge_window))

    def groupby(self, key: str) -> "GroupedData":
        """Hash-based group-by (reference: ``Dataset.groupby`` →
        ``grouped_data.py``)."""
        return GroupedData(self, key)

    def aggregate(self, *aggs) -> Dict[str, Any]:
        """Whole-dataset aggregation without a key (reference:
        ``Dataset.aggregate``): each block folds to constant-key agg
        state, merged in remote tasks, finalized here."""
        from .shuffle import groupby_aggregate_blocks

        refs = [_add_const_key.remote(r)
                for r in self.streaming_block_refs()]
        out_refs = groupby_aggregate_blocks(refs, "__all__", list(aggs),
                                            num_partitions=1)
        blk = B.block_concat([b for b in get(out_refs)
                              if B.block_num_rows(b)])
        return {agg.name: blk[agg.name][0] if B.block_num_rows(blk)
                else None for agg in aggs}

    def split(self, n: int) -> List["Dataset"]:
        """Split into n datasets by round-robin over blocks (reference:
        ``Dataset.split`` for per-worker ingest)."""
        mat = self.materialize()
        refs = mat._block_refs or []
        return [Dataset(block_refs=refs[i::n]) for i in range(n)]

    def limit(self, n: int) -> "Dataset":
        out_refs = []
        remaining = n
        for ref in self.streaming_block_refs():
            blk_rows = B.block_num_rows(get(ref))
            if blk_rows <= remaining:
                out_refs.append(ref)
                remaining -= blk_rows
            else:
                out_refs.append(_slice_block.remote(ref, 0, remaining))
                remaining = 0
            if remaining <= 0:
                break
        return Dataset(block_refs=out_refs)

    def union(self, other: "Dataset") -> "Dataset":
        return Dataset(block_refs=(self.materialize()._block_refs
                                   + other.materialize()._block_refs))

    # ----------------------------------------------------------- consumption
    def iter_blocks(self) -> Iterator[Block]:
        for ref in self.streaming_block_refs():
            yield get(ref)

    def streaming_split(self, n: int, *, queue_size: int = 4):
        """n concurrently-consumable DataIterator shards (reference:
        ``Dataset.streaming_split`` / Train ingest ``data_config.py``);
        see ``data/iterator.py``."""
        from .iterator import streaming_split
        return streaming_split(self, n, queue_size=queue_size)

    def schema(self) -> Dict[str, str]:
        """Column -> dtype/shape of the first block (reference:
        ``Dataset.schema``); consumes one block of the stream."""
        for blk in self.iter_blocks():
            if blk:
                return B.block_metadata(blk).schema
        return {}

    def _windowed_apply(self, task_fn, window: int = 16) -> Iterator[Any]:
        """Map every block ref through ``task_fn`` with a bounded
        in-flight window, dropping each block ref as its result is
        consumed — aggregate queries must not defeat the streaming
        executor's residency bound by holding every ref at once."""
        in_flight: "deque" = deque()
        for ref in self.streaming_block_refs():
            in_flight.append(task_fn.remote(ref))
            del ref
            if len(in_flight) >= window:
                yield get(in_flight.popleft())
        while in_flight:
            yield get(in_flight.popleft())

    def count(self) -> int:
        """Total rows; counted block-by-block in remote tasks so the
        payloads never concentrate on the driver."""
        return int(sum(self._windowed_apply(_count_block)))

    def stats(self) -> Dict[str, Any]:
        """num_blocks / num_rows / size_bytes, metadata computed
        block-by-block in remote tasks (reference: BlockMetadata
        aggregation)."""
        n_blocks = n_rows = n_bytes = 0
        schema: Dict[str, str] = {}
        for m in self._windowed_apply(_meta_block):
            if not n_blocks:
                schema = m.schema
            n_blocks += 1
            n_rows += m.num_rows
            n_bytes += m.size_bytes
        return {"num_blocks": n_blocks, "num_rows": n_rows,
                "size_bytes": n_bytes, "schema": schema}

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for blk in self.iter_blocks():
            yield from B.block_rows(blk)

    def iter_batches(self, *, batch_size: int = 256,
                     drop_last: bool = False) -> Iterator[Block]:
        """Re-batch across block boundaries."""
        buf: List[Block] = []
        buffered = 0
        for blk in self.iter_blocks():
            if not B.block_num_rows(blk):
                continue
            buf.append(blk)
            buffered += B.block_num_rows(blk)
            while buffered >= batch_size:
                merged = B.block_concat(buf)
                yield B.block_slice(merged, 0, batch_size)
                rest = B.block_slice(merged, batch_size,
                                     B.block_num_rows(merged))
                buf = [rest] if B.block_num_rows(rest) else []
                buffered = B.block_num_rows(rest)
        if buffered and not drop_last:
            yield B.block_concat(buf)

    def iter_device_batches(self, *, batch_size: int,
                            sharding: Any = None,
                            drop_last: bool = True) -> Iterator[Any]:
        """Double-buffered device prefetch: host batch i+1 is transferred
        while batch i computes (the TPU ingest pattern; reference
        analogue: ``train/_internal/data_config.py`` streaming splits +
        torch dataloader prefetch)."""
        import jax

        def to_device(blk: Block):
            arrs = {k: jax.device_put(v, sharding) for k, v in blk.items()}
            return arrs

        it = self.iter_batches(batch_size=batch_size, drop_last=drop_last)
        prev = None
        for blk in it:
            nxt = to_device(blk)       # async transfer starts now
            if prev is not None:
                yield prev
            prev = nxt
        if prev is not None:
            yield prev

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(B.block_num_rows(b) for b in self.iter_blocks())

    def schema(self) -> Dict[str, str]:
        for blk in self.iter_blocks():
            if B.block_num_rows(blk):
                return {k: str(v.dtype) for k, v in blk.items()}
        return {}

    def num_blocks(self) -> int:
        return self._num_input_blocks()

    def __repr__(self):
        stages = "+".join(s[0] for s in self._stages) or "read"
        return (f"Dataset(blocks={self._num_input_blocks()}, "
                f"stages={stages})")


class GroupedData:
    """Result of ``Dataset.groupby(key)`` (reference:
    ``python/ray/data/grouped_data.py``): aggregations ride the
    push-based shuffle engine — raw rows hash-partition by key, fold
    into per-group state at first merge, and finalize into one output
    block per partition."""

    def __init__(self, dataset: "Dataset", key: str):
        self._ds = dataset
        self._key = key

    def aggregate(self, *aggs, num_partitions: Optional[int] = None,
                  merge_window: int = 8) -> "Dataset":
        from .shuffle import groupby_aggregate_blocks
        refs = list(self._ds.streaming_block_refs())
        return Dataset(block_refs=groupby_aggregate_blocks(
            refs, self._key, list(aggs), num_partitions=num_partitions,
            merge_window=merge_window))

    def map_groups(self, fn: Callable, *, num_partitions: Optional[int]
                   = None, merge_window: int = 8) -> "Dataset":
        """Apply ``fn(group_block) -> block/rows`` once per group. Each
        group lands whole in one partition via the hash shuffle."""
        from .shuffle import map_groups_blocks
        refs = list(self._ds.streaming_block_refs())
        return Dataset(block_refs=map_groups_blocks(
            refs, self._key, fn, num_partitions=num_partitions,
            merge_window=merge_window))

    # convenience single-agg forms (reference: GroupedData.count/...)
    def count(self) -> "Dataset":
        from .aggregate import Count
        return self.aggregate(Count())

    def sum(self, on: str) -> "Dataset":
        from .aggregate import Sum
        return self.aggregate(Sum(on))

    def mean(self, on: str) -> "Dataset":
        from .aggregate import Mean
        return self.aggregate(Mean(on))

    def min(self, on: str) -> "Dataset":
        from .aggregate import Min
        return self.aggregate(Min(on))

    def max(self, on: str) -> "Dataset":
        from .aggregate import Max
        return self.aggregate(Max(on))

    def std(self, on: str, ddof: int = 1) -> "Dataset":
        from .aggregate import Std
        return self.aggregate(Std(on, ddof))


def _extend_dataset_conveniences():
    """Column/row conveniences riding existing operators (reference:
    ``Dataset.select_columns/drop_columns/add_column/rename_columns``
    and the scalar ``sum/min/max/mean/std/unique`` reducers of
    ``python/ray/data/dataset.py``)."""

    def select_columns(self, cols: List[str]) -> "Dataset":
        cols = list(cols)
        return self.map_batches(
            lambda b: {k: b[k] for k in cols})

    def drop_columns(self, cols: List[str]) -> "Dataset":
        drop = set(cols)
        return self.map_batches(
            lambda b: {k: v for k, v in b.items() if k not in drop})

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def add(b):
            out = dict(b)
            out[name] = np.asarray(fn(b))
            return out
        return self.map_batches(add)

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self.map_batches(
            lambda b: {mapping.get(k, k): v for k, v in b.items()})

    def _scalar(self, agg):
        return self.aggregate(agg)[agg.name]

    def sum(self, on: str):
        from .aggregate import Sum
        return _scalar(self, Sum(on))

    def min(self, on: str):
        from .aggregate import Min
        return _scalar(self, Min(on))

    def max(self, on: str):
        from .aggregate import Max
        return _scalar(self, Max(on))

    def mean(self, on: str):
        from .aggregate import Mean
        return _scalar(self, Mean(on))

    def std(self, on: str, ddof: int = 1):
        from .aggregate import Std
        return _scalar(self, Std(on, ddof))

    def unique(self, column: str) -> List[Any]:
        parts = [np.unique(np.asarray(blk[column]))
                 for blk in self.iter_blocks() if B.block_num_rows(blk)]
        if not parts:
            return []
        return np.unique(np.concatenate(parts)).tolist()

    for fn in (select_columns, drop_columns, add_column, rename_columns,
               sum, min, max, mean, std, unique):
        setattr(Dataset, fn.__name__, fn)


_extend_dataset_conveniences()
