"""Distributed dataset writers: one output file per block, written by
remote tasks.

Reference: ``python/ray/data/dataset.py`` ``write_csv/write_json/
write_parquet/write_numpy`` — the write is a consuming operator: each
block is serialized by the task holding it (payloads never concentrate
on the driver), files land as ``part-NNNNN.<ext>``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List

import numpy as np

from ..api import remote
from . import block as B

Block = B.Block


def _part_path(path: str, index: int, ext: str) -> str:
    os.makedirs(path, exist_ok=True)
    return os.path.join(path, f"part-{index:05d}{ext}")


@remote
def _write_csv_block(blk: Block, path: str, index: int) -> str:
    import csv
    out = _part_path(path, index, ".csv")
    keys = list(blk)
    with open(out, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(keys)
        for row in B.block_rows(blk):
            w.writerow([row[k] for k in keys])
    return out


@remote
def _write_json_block(blk: Block, path: str, index: int) -> str:
    import json
    out = _part_path(path, index, ".jsonl")
    with open(out, "w") as f:
        for row in B.block_rows(blk):
            f.write(json.dumps(
                {k: (v.tolist() if isinstance(v, np.generic)
                     or isinstance(v, np.ndarray) else v)
                 for k, v in row.items()}) + "\n")
    return out


@remote
def _write_parquet_block(blk: Block, path: str, index: int) -> str:
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq
    except ImportError as e:
        raise ImportError(
            "write_parquet requires pyarrow, which is not available "
            "in this environment") from e
    out = _part_path(path, index, ".parquet")
    pq.write_table(pa.table({k: pa.array(v) for k, v in blk.items()}),
                   out)
    return out


@remote
def _write_numpy_block(blk: Block, path: str, index: int,
                       column: str) -> str:
    out = _part_path(path, index, ".npy")
    np.save(out, np.asarray(blk[column]))
    return out


def install_writers(dataset_cls) -> None:
    """Attach write_* methods to Dataset (kept out of dataset.py to
    mirror the read_api/write split of the reference)."""
    from .. import get

    def _write(self, task, path: str, **kw) -> List[str]:
        files = []
        # windowed like every consuming operator: writes stream, the
        # driver holds refs for at most one window
        pending: List[Any] = []
        for i, ref in enumerate(self.streaming_block_refs()):
            pending.append(task.remote(ref, path, i, **kw))
            if len(pending) >= 8:
                files.extend(get(pending))
                pending = []
        files.extend(get(pending) if pending else [])
        return files

    def write_csv(self, path: str) -> List[str]:
        return _write(self, _write_csv_block, path)

    def write_json(self, path: str) -> List[str]:
        return _write(self, _write_json_block, path)

    def write_parquet(self, path: str) -> List[str]:
        return _write(self, _write_parquet_block, path)

    def write_numpy(self, path: str, column: str = "data") -> List[str]:
        return _write(self, _write_numpy_block, path, column=column)

    for fn in (write_csv, write_json, write_parquet, write_numpy):
        setattr(dataset_cls, fn.__name__, fn)
