"""Datasource readers (reference: ``python/ray/data/read_api.py`` +
``data/datasource/`` parquet/csv/json readers). Each file (or range
shard) becomes one read task — reads execute inside the streaming
executor, not eagerly on the driver.
"""

from __future__ import annotations

import builtins
import functools
from typing import Any, List, Optional, Sequence, Union

import numpy as np

from .block import block_from_rows, normalize_block
from .dataset import Dataset


def range(n: int, *, num_blocks: Optional[int] = None) -> Dataset:
    num_blocks = num_blocks or min(max(1, n // 1000), 64)
    bounds = np.linspace(0, n, num_blocks + 1, dtype=np.int64)

    def make(lo: int, hi: int):
        return {"id": np.arange(lo, hi, dtype=np.int64)}

    return Dataset(sources=[functools.partial(make, int(lo), int(hi))
                            for lo, hi in zip(bounds[:-1], bounds[1:])])


def range_tensor(n: int, *, shape=(1,),
                 num_blocks: Optional[int] = None) -> Dataset:
    num_blocks = num_blocks or min(max(1, n // 1000), 64)
    bounds = np.linspace(0, n, num_blocks + 1, dtype=np.int64)

    def make(lo: int, hi: int):
        base = np.arange(lo, hi, dtype=np.int64)
        data = np.broadcast_to(base.reshape((-1,) + (1,) * len(shape)),
                               (hi - lo,) + tuple(shape)).copy()
        return {"data": data}

    return Dataset(sources=[functools.partial(make, int(lo), int(hi))
                            for lo, hi in zip(bounds[:-1], bounds[1:])])


def from_items(items: Sequence[Any], *,
               num_blocks: int = 4) -> Dataset:
    items = list(items)
    rows = [it if isinstance(it, dict) else {"item": it} for it in items]
    num_blocks = max(1, min(num_blocks, len(rows) or 1))
    chunks = np.array_split(np.arange(len(rows)), num_blocks)

    def make(idx: np.ndarray):
        return block_from_rows([rows[i] for i in idx])

    return Dataset(sources=[functools.partial(make, c) for c in chunks
                            if len(c)])


def from_numpy(arrays: Union[np.ndarray, Dict[str, np.ndarray]],
               *, num_blocks: int = 4) -> Dataset:
    blk = normalize_block(arrays)
    n = len(next(iter(blk.values()))) if blk else 0
    num_blocks = max(1, min(num_blocks, n or 1))
    bounds = np.linspace(0, n, num_blocks + 1, dtype=np.int64)

    def make(lo: int, hi: int):
        return {k: v[lo:hi] for k, v in blk.items()}

    return Dataset(sources=[functools.partial(make, int(lo), int(hi))
                            for lo, hi in zip(bounds[:-1], bounds[1:])])


def _from_datasource(ds) -> Dataset:
    """Dataset over a FileBasedDatasource: one STREAMING read task per
    file, yielding bounded-row blocks as the read progresses."""
    return Dataset(sources=ds.sources(), source_streaming=True)


def read_csv(paths: Union[str, Sequence[str]], **kw) -> Dataset:
    from .datasource import CSVDatasource
    return _from_datasource(CSVDatasource(paths, **kw))


def read_json(paths: Union[str, Sequence[str]], *, lines: bool = True,
              **kw) -> Dataset:
    from .datasource import JSONDatasource
    return _from_datasource(JSONDatasource(paths, lines=lines, **kw))


def read_parquet(paths: Union[str, Sequence[str]], *,
                 columns: Optional[List[str]] = None, **kw) -> Dataset:
    """Parquet via pyarrow (gated so the core package has no hard
    dependency); reads stream at row-group granularity."""
    from .datasource import ParquetDatasource
    return _from_datasource(ParquetDatasource(paths, columns=columns, **kw))


def read_text(paths: Union[str, Sequence[str]], **kw) -> Dataset:
    """One row per line: {"text": str} (reference:
    ``data/read_api.py`` read_text -> text_datasource)."""
    from .datasource import TextDatasource
    return _from_datasource(TextDatasource(paths, **kw))


def read_numpy(paths: Union[str, Sequence[str]], **kw) -> Dataset:
    """.npy -> {"data": rows}; .npz -> one column per entry."""
    from .datasource import NumpyDatasource
    return _from_datasource(NumpyDatasource(paths, **kw))


def read_binary_files(paths: Union[str, Sequence[str]], **kw) -> Dataset:
    """One row per file: {"bytes", "path"}."""
    from .datasource import BinaryDatasource
    return _from_datasource(BinaryDatasource(paths, **kw))


def read_images(paths: Union[str, Sequence[str]], **kw) -> Dataset:
    """{"image": HWC array, "path"} rows via PIL (gated)."""
    from .datasource import ImageDatasource
    return _from_datasource(ImageDatasource(paths, **kw))


def read_tfrecords(paths: Union[str, Sequence[str]], **kw) -> Dataset:
    """tf.train.Example tfrecords, parsed without a tensorflow
    dependency (see ``datasource.TFRecordDatasource``)."""
    from .datasource import TFRecordDatasource
    return _from_datasource(TFRecordDatasource(paths, **kw))


def from_generators(generators: Sequence[Any]) -> Dataset:
    """Dataset whose sources are block GENERATORS: each callable yields
    blocks one at a time, and every block leaves the producing task the
    moment it is yielded (``num_returns="streaming"``), so a source that
    produces 1000 blocks never holds more than the backpressure window
    in flight. Reference analogue: streaming read tasks reporting blocks
    through ``ObjectRefGenerator`` (``_raylet.pyx:252``).

    Example::

        def read_shard(path):
            def gen():
                for chunk in open_chunks(path):
                    yield chunk_to_block(chunk)
            return gen

        ds = ray_tpu.data.from_generators([read_shard(p) for p in paths])
    """
    gens = list(generators)
    if not gens:
        raise ValueError("from_generators needs at least one generator")
    return Dataset(sources=gens, source_streaming=True)
