"""Datasource readers (reference: ``python/ray/data/read_api.py`` +
``data/datasource/`` parquet/csv/json readers). Each file (or range
shard) becomes one read task — reads execute inside the streaming
executor, not eagerly on the driver.
"""

from __future__ import annotations

import builtins
import functools
import glob as _glob
import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .block import Block, block_from_rows, normalize_block
from .dataset import Dataset


def _expand_paths(paths: Union[str, Sequence[str]],
                  suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, f"*{suffix}" if suffix else "*")
            out.extend(sorted(_glob.glob(pat)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def range(n: int, *, num_blocks: Optional[int] = None) -> Dataset:
    num_blocks = num_blocks or min(max(1, n // 1000), 64)
    bounds = np.linspace(0, n, num_blocks + 1, dtype=np.int64)

    def make(lo: int, hi: int):
        return {"id": np.arange(lo, hi, dtype=np.int64)}

    return Dataset(sources=[functools.partial(make, int(lo), int(hi))
                            for lo, hi in zip(bounds[:-1], bounds[1:])])


def range_tensor(n: int, *, shape=(1,),
                 num_blocks: Optional[int] = None) -> Dataset:
    num_blocks = num_blocks or min(max(1, n // 1000), 64)
    bounds = np.linspace(0, n, num_blocks + 1, dtype=np.int64)

    def make(lo: int, hi: int):
        base = np.arange(lo, hi, dtype=np.int64)
        data = np.broadcast_to(base.reshape((-1,) + (1,) * len(shape)),
                               (hi - lo,) + tuple(shape)).copy()
        return {"data": data}

    return Dataset(sources=[functools.partial(make, int(lo), int(hi))
                            for lo, hi in zip(bounds[:-1], bounds[1:])])


def from_items(items: Sequence[Any], *,
               num_blocks: int = 4) -> Dataset:
    items = list(items)
    rows = [it if isinstance(it, dict) else {"item": it} for it in items]
    num_blocks = max(1, min(num_blocks, len(rows) or 1))
    chunks = np.array_split(np.arange(len(rows)), num_blocks)

    def make(idx: np.ndarray):
        return block_from_rows([rows[i] for i in idx])

    return Dataset(sources=[functools.partial(make, c) for c in chunks
                            if len(c)])


def from_numpy(arrays: Union[np.ndarray, Dict[str, np.ndarray]],
               *, num_blocks: int = 4) -> Dataset:
    blk = normalize_block(arrays)
    n = len(next(iter(blk.values()))) if blk else 0
    num_blocks = max(1, min(num_blocks, n or 1))
    bounds = np.linspace(0, n, num_blocks + 1, dtype=np.int64)

    def make(lo: int, hi: int):
        return {k: v[lo:hi] for k, v in blk.items()}

    return Dataset(sources=[functools.partial(make, int(lo), int(hi))
                            for lo, hi in zip(bounds[:-1], bounds[1:])])


def read_csv(paths: Union[str, Sequence[str]], **kw) -> Dataset:
    files = _expand_paths(paths, ".csv")

    def read_one(path: str) -> Block:
        import csv
        with open(path, newline="") as f:
            rows = list(csv.DictReader(f))
        blk = block_from_rows(rows)
        # numeric columns parse as numbers (csv gives strings)
        out = {}
        for k, v in blk.items():
            try:
                out[k] = v.astype(np.int64)
            except ValueError:
                try:
                    out[k] = v.astype(np.float64)
                except ValueError:
                    out[k] = v
        return out

    return Dataset(sources=[functools.partial(read_one, p) for p in files])


def read_json(paths: Union[str, Sequence[str]], *, lines: bool = True,
              **kw) -> Dataset:
    files = _expand_paths(paths, ".jsonl" if lines else ".json")

    def read_one(path: str) -> Block:
        import json
        with open(path) as f:
            if lines:
                rows = [json.loads(line) for line in f if line.strip()]
            else:
                data = json.load(f)
                rows = data if isinstance(data, list) else [data]
        return block_from_rows(rows)

    return Dataset(sources=[functools.partial(read_one, p) for p in files])


def read_parquet(paths: Union[str, Sequence[str]], *,
                 columns: Optional[List[str]] = None, **kw) -> Dataset:
    """Parquet via pyarrow if present, else torch-free fallback error.

    (pyarrow ships with the baked pandas/pyarrow stack when available;
    gated so the core package has no hard dependency.)
    """
    files = _expand_paths(paths, ".parquet")

    def read_one(path: str) -> Block:
        try:
            import pyarrow.parquet as pq
        except ImportError as e:
            raise ImportError(
                "read_parquet requires pyarrow, which is not available "
                "in this environment") from e
        table = pq.read_table(path, columns=columns)
        return {name: np.asarray(col)
                for name, col in zip(table.column_names,
                                     table.to_pydict().values())}

    return Dataset(sources=[functools.partial(read_one, p) for p in files])


def from_generators(generators: Sequence[Any]) -> Dataset:
    """Dataset whose sources are block GENERATORS: each callable yields
    blocks one at a time, and every block leaves the producing task the
    moment it is yielded (``num_returns="streaming"``), so a source that
    produces 1000 blocks never holds more than the backpressure window
    in flight. Reference analogue: streaming read tasks reporting blocks
    through ``ObjectRefGenerator`` (``_raylet.pyx:252``).

    Example::

        def read_shard(path):
            def gen():
                for chunk in open_chunks(path):
                    yield chunk_to_block(chunk)
            return gen

        ds = ray_tpu.data.from_generators([read_shard(p) for p in paths])
    """
    gens = list(generators)
    if not gens:
        raise ValueError("from_generators needs at least one generator")
    return Dataset(sources=gens, source_streaming=True)
