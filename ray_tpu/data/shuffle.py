"""Push-based shuffle engine: the all-to-all half of the Data layer.

Reference: ``python/ray/data/_internal/push_based_shuffle.py`` (two-stage
pipelined shuffle: map tasks partition blocks, merge tasks combine
chunks round by round so reducer memory stays bounded) and
``planner/exchange/sort_task_spec.py`` (sample → boundaries → range
partition). The design here keeps the reference's round structure but
rides this runtime's primitives: map tasks ``put()`` each partition
chunk into the shm object store and return only refs, so a reducer
pulls exactly its partition's bytes; merge tasks chain on their own
previous partial, so round r+1's maps overlap round r's merges without
any driver-side barrier.

Memory bound: live chunk objects never exceed one round's output
(``merge_window`` maps × ``num_partitions`` chunks) plus the P partials
— asserted by ``ShuffleStats.peak_live_chunk_refs`` in tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from .. import get
from ..api import remote
from . import block as B

Block = B.Block

DEFAULT_MERGE_WINDOW = 8


@dataclass
class ShuffleStats:
    num_maps: int = 0
    num_rounds: int = 0
    num_partitions: int = 0
    peak_live_chunk_refs: int = 0
    extras: Dict[str, Any] = field(default_factory=dict)


@remote
def _shuffle_map(blk: Block, partition_fn: Callable,
                 num_partitions: int, map_index: int) -> List[Any]:
    """Partition one block; each chunk goes to the object store
    separately so reducers fetch only their own partition's bytes."""
    from .. import put
    chunks = partition_fn(blk, num_partitions, map_index)
    assert len(chunks) == num_partitions
    return [put(c) for c in chunks]


@remote
def _shuffle_merge(merge_fn: Callable[[Optional[Block], List[Block]], Block],
                   partial: Optional[Block], *chunks: Block) -> Block:
    return merge_fn(partial, list(chunks))


def shuffle_exec(block_refs: Iterable[Any], *, num_partitions: int,
                 partition_fn: Callable[[Block, int], List[Block]],
                 merge_fn: Callable[[Optional[Block], List[Block]], Block],
                 merge_window: int = DEFAULT_MERGE_WINDOW,
                 stats: Optional[ShuffleStats] = None) -> List[Any]:
    """Run the two-stage shuffle; returns one partial-ref per partition
    (in partition order). The caller chains finalize tasks on them.

    Rounds pipeline themselves: each partition's merge chains on that
    partition's previous partial ref only, so the scheduler runs round
    r merges concurrently with round r+1 maps.
    """
    st = stats if stats is not None else ShuffleStats()
    st.num_partitions = num_partitions
    partials: List[Optional[Any]] = [None] * num_partitions
    live_chunks = 0

    def flush(round_chunk_lists: List[List[Any]]) -> None:
        nonlocal live_chunks
        if not round_chunk_lists:
            return
        st.num_rounds += 1
        for p in range(num_partitions):
            chunks = [lst[p] for lst in round_chunk_lists]
            partials[p] = _shuffle_merge.remote(merge_fn, partials[p],
                                                *chunks)
        # chunk refs drop here; once each merge consumes its inputs the
        # refcount frees the chunk objects — residency stays one round
        live_chunks -= sum(len(lst) for lst in round_chunk_lists)

    pending_maps: List[Any] = []
    round_lists: List[List[Any]] = []
    for ref in block_refs:
        pending_maps.append(_shuffle_map.remote(ref, partition_fn,
                                                num_partitions,
                                                st.num_maps))
        st.num_maps += 1
        if len(pending_maps) >= merge_window:
            round_lists = get(pending_maps)
            pending_maps = []
            live_chunks += sum(len(lst) for lst in round_lists)
            st.peak_live_chunk_refs = max(st.peak_live_chunk_refs,
                                          live_chunks)
            flush(round_lists)
    if pending_maps:
        round_lists = get(pending_maps)
        live_chunks += sum(len(lst) for lst in round_lists)
        st.peak_live_chunk_refs = max(st.peak_live_chunk_refs,
                                      live_chunks)
        flush(round_lists)
    return partials


# --------------------------------------------------------------- sort

def _scatter(blk: Block, part: np.ndarray, num_partitions: int
             ) -> List[Block]:
    """Split a block into per-partition sub-blocks by index array."""
    return [B.block_take(blk, np.nonzero(part == p)[0])
            for p in range(num_partitions)]


def _empty_parts(num_partitions: int) -> List[Block]:
    return [{} for _ in range(num_partitions)]



def _range_partition(boundaries: np.ndarray, key: str, descending: bool
                     ) -> Callable:
    def fn(blk: Block, num_partitions: int, map_index: int) -> List[Block]:
        if not B.block_num_rows(blk):
            return _empty_parts(num_partitions)
        keys = np.asarray(blk[key])
        part = np.searchsorted(boundaries, keys, side="right")
        if descending:
            part = (num_partitions - 1) - part
        return _scatter(blk, part, num_partitions)
    return fn


def _concat_merge(partial: Optional[Block], chunks: List[Block]) -> Block:
    parts = ([partial] if partial else []) + chunks
    return B.block_concat(parts)


@remote
def _sort_finalize(blk: Block, key: str, descending: bool) -> Block:
    if not B.block_num_rows(blk):
        return blk
    order = np.argsort(np.asarray(blk[key]), kind="stable")
    if descending:
        order = order[::-1]
    return B.block_take(blk, order)


@remote
def _sample_keys(blk: Block, key: str, k: int, seed: int) -> np.ndarray:
    n = B.block_num_rows(blk)
    if not n:
        return np.asarray([])
    rng = np.random.default_rng(seed)
    idx = rng.choice(n, size=min(k, n), replace=False)
    return np.asarray(blk[key])[idx]


def sort_blocks(block_refs: List[Any], key: str, *,
                descending: bool = False,
                num_partitions: Optional[int] = None,
                merge_window: int = DEFAULT_MERGE_WINDOW,
                sample_size: int = 64,
                stats: Optional[ShuffleStats] = None) -> List[Any]:
    """Distributed sort: sample → range boundaries → shuffle → per-
    partition sort. Output block p holds the p-th key range; global
    order is the block order (reference: ``sort_task_spec.py``)."""
    if not block_refs:
        return []
    P = num_partitions or min(len(block_refs), 16)
    sampled = [s for s in get([_sample_keys.remote(r, key, sample_size, i)
                               for i, r in enumerate(block_refs)])
               if len(s)]
    if sampled:
        ordered = np.sort(np.concatenate(sampled))
        # index-based quantiles work for any orderable dtype (strings
        # included), unlike np.quantile
        idx = [int(round(q * (len(ordered) - 1)))
               for q in np.linspace(0, 1, P + 1)[1:-1]]
        boundaries = ordered[idx]
    else:
        boundaries = np.asarray([])
    partials = shuffle_exec(
        block_refs, num_partitions=P,
        partition_fn=_range_partition(boundaries, key, descending),
        merge_fn=_concat_merge, merge_window=merge_window, stats=stats)
    return [_sort_finalize.remote(p, key, descending) for p in partials]


# ---------------------------------------------------- random shuffle

def _random_partition(seed: int) -> Callable:
    def fn(blk: Block, num_partitions: int, map_index: int) -> List[Block]:
        n = B.block_num_rows(blk)
        if not n:
            return _empty_parts(num_partitions)
        rng = np.random.default_rng((seed, map_index))
        part = rng.integers(0, num_partitions, size=n)
        return _scatter(blk, part, num_partitions)
    return fn


@remote
def _permute_finalize(blk: Block, seed: int) -> Block:
    n = B.block_num_rows(blk)
    if not n:
        return blk
    return B.block_take(blk, np.random.default_rng(seed).permutation(n))


def random_shuffle_blocks(block_refs: List[Any], *,
                          seed: Optional[int] = None,
                          num_partitions: Optional[int] = None,
                          merge_window: int = DEFAULT_MERGE_WINDOW,
                          stats: Optional[ShuffleStats] = None
                          ) -> List[Any]:
    """True all-to-all row shuffle (reference:
    ``push_based_shuffle.py``): every output block draws rows from
    every input block, then permutes locally."""
    if not block_refs:
        return []
    P = num_partitions or len(block_refs)
    base = int(seed if seed is not None else
               np.random.default_rng().integers(2**31))
    # distinct per-map streams: partition seed mixes in the map index
    refs = list(block_refs)
    partials = []
    idx_partials = shuffle_exec(
        refs, num_partitions=P,
        partition_fn=_random_partition(base),
        merge_fn=_concat_merge, merge_window=merge_window, stats=stats)
    for p, ref in enumerate(idx_partials):
        partials.append(_permute_finalize.remote(ref, base + 7919 * (p + 1)))
    return partials


# --------------------------------------------------------- group-by

def _hash_partition(key: str) -> Callable:
    def fn(blk: Block, num_partitions: int, map_index: int) -> List[Block]:
        if not B.block_num_rows(blk):
            return _empty_parts(num_partitions)
        keys = np.asarray(blk[key])
        if keys.dtype.kind in "iub":
            h = keys.astype(np.uint64)
        elif keys.dtype.kind == "f":
            k = keys.astype(np.float64)
            # canonicalize bit patterns of equal keys: -0.0 == 0.0 and
            # all NaN payloads must land in one partition
            k = np.where(k == 0.0, 0.0, k)
            k = np.where(np.isnan(k), np.nan, k)
            h = k.view(np.uint64)
        else:
            # str/bytes/object: Python's hash() is per-process salted —
            # maps in different workers would split one group across
            # partitions; crc32 is process-stable
            import zlib
            h = np.asarray([zlib.crc32(str(x).encode()) for x in keys],
                           dtype=np.uint64)
        h = (h ^ (h >> np.uint64(33))) * np.uint64(0xff51afd7ed558ccd)
        part = (h % np.uint64(num_partitions)).astype(np.int64)
        return _scatter(blk, part, num_partitions)
    return fn


def _agg_state_merge(key: str, aggs) -> Callable:
    """Merge fn for groupby: partial state blocks re-group on the key
    and each aggregate combines its namespaced state columns."""
    def fn(partial: Optional[Block], chunks: List[Block]) -> Block:
        # chunks are RAW row blocks on the first touch; partials are
        # state blocks (marked by the __key__ column)
        states = [partial] if partial else []
        for c in chunks:
            if not B.block_num_rows(c):
                continue
            keys = np.asarray(c[key])
            uniq, gid = np.unique(keys, return_inverse=True)
            st: Block = {"__key__": uniq}
            for i, agg in enumerate(aggs):
                for name, col in agg.init_state(c, gid, len(uniq)).items():
                    st[f"a{i}__{name}"] = col
            states.append(st)
        states = [s for s in states if B.block_num_rows(s)]
        if not states:
            return {}
        if len(states) == 1:
            return states[0]
        allk = np.concatenate([s["__key__"] for s in states])
        uniq, gid = np.unique(allk, return_inverse=True)
        out: Block = {"__key__": uniq}
        for i, agg in enumerate(aggs):
            prefix = f"a{i}__"
            cat = {nm[len(prefix):]: np.concatenate(
                       [s[nm] for s in states])
                   for nm in states[0] if nm.startswith(prefix)}
            for name, col in agg.combine(cat, gid, len(uniq)).items():
                out[prefix + name] = col
        return out
    return fn


@remote
def _agg_finalize(state: Block, key: str, aggs) -> Block:
    if not B.block_num_rows(state):
        return {}
    out: Block = {key: state["__key__"]}
    for i, agg in enumerate(aggs):
        prefix = f"a{i}__"
        cols = {nm[len(prefix):]: state[nm]
                for nm in state if nm.startswith(prefix)}
        out[agg.name] = agg.finalize(cols)
    return out


def groupby_aggregate_blocks(block_refs: List[Any], key: str, aggs, *,
                             num_partitions: Optional[int] = None,
                             merge_window: int = DEFAULT_MERGE_WINDOW,
                             stats: Optional[ShuffleStats] = None
                             ) -> List[Any]:
    """Hash-shuffle + combine: map chunks carry raw rows, merges fold
    them into per-group state immediately (map-side pre-aggregation
    happens at the first merge a chunk meets), so partial size is
    O(groups), not O(rows)."""
    if not block_refs:
        return []
    P = num_partitions or min(len(block_refs), 16)
    partials = shuffle_exec(
        block_refs, num_partitions=P, partition_fn=_hash_partition(key),
        merge_fn=_agg_state_merge(key, aggs),
        merge_window=merge_window, stats=stats)
    return [_agg_finalize.remote(p, key, aggs) for p in partials]


@remote
def _map_groups_finalize(blk: Block, key: str, fn: Callable) -> Block:
    if not B.block_num_rows(blk):
        return {}
    keys = np.asarray(blk[key])
    order = np.argsort(keys, kind="stable")
    sorted_blk = B.block_take(blk, order)
    sorted_keys = keys[order]
    bounds = np.nonzero(np.concatenate(
        ([True], sorted_keys[1:] != sorted_keys[:-1])))[0]
    bounds = np.append(bounds, len(sorted_keys))
    outs = []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        group = B.block_slice(sorted_blk, int(lo), int(hi))
        outs.append(B.normalize_block(fn(group)))
    return B.block_concat(outs)


def map_groups_blocks(block_refs: List[Any], key: str, fn: Callable, *,
                      num_partitions: Optional[int] = None,
                      merge_window: int = DEFAULT_MERGE_WINDOW,
                      stats: Optional[ShuffleStats] = None) -> List[Any]:
    """Hash-shuffle rows so each group lands whole in one partition,
    then apply ``fn`` per group (reference: ``GroupedData.map_groups``)."""
    if not block_refs:
        return []
    P = num_partitions or min(len(block_refs), 16)
    partials = shuffle_exec(
        block_refs, num_partitions=P, partition_fn=_hash_partition(key),
        merge_fn=_concat_merge, merge_window=merge_window, stats=stats)
    return [_map_groups_finalize.remote(p, key, fn) for p in partials]
