"""Vectorized aggregation functions for groupby/aggregate.

Reference: ``python/ray/data/aggregate.py`` (AggregateFn protocol:
init/accumulate/merge/finalize, with Count/Sum/Min/Max/Mean/Std
built-ins) and ``grouped_data.py``. The protocol here is columnar and
segment-vectorized instead of row-accumulated: an aggregate maps a
whole block to fixed-width per-group STATE columns (via unsorted
segment ops like ``np.add.at``), states merge by re-grouping, and
finalize converts state to the result column — no Python-per-row work,
the same shape as a jax ``segment_sum``.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

Block = Dict[str, np.ndarray]


def _seg_sum(values: np.ndarray, gid: np.ndarray, n: int) -> np.ndarray:
    # integer columns accumulate in int64 (casting through float64
    # would silently round sums beyond 2^53); floats in float64
    kind = values.dtype.kind
    acc = (np.uint64 if kind == "u" else
           np.int64 if kind in "ib" else
           np.float64 if kind == "f" else values.dtype)
    out = np.zeros(n, dtype=acc)
    np.add.at(out, gid, values)
    return out


class AggregateFn:
    """One aggregation. State columns are namespaced by the engine."""

    name: str = "agg"

    def init_state(self, blk: Block, gid: np.ndarray, n: int
                   ) -> Dict[str, np.ndarray]:
        """Block rows → per-group state columns (each length n)."""
        raise NotImplementedError

    def combine(self, state: Dict[str, np.ndarray], gid: np.ndarray,
                n: int) -> Dict[str, np.ndarray]:
        """Re-group state rows (from concatenated partials) into n
        groups."""
        raise NotImplementedError

    def finalize(self, state: Dict[str, np.ndarray]) -> np.ndarray:
        raise NotImplementedError


class Count(AggregateFn):
    def __init__(self):
        self.name = "count()"

    def init_state(self, blk, gid, n):
        return {"c": np.bincount(gid, minlength=n).astype(np.int64)}

    def combine(self, state, gid, n):
        return {"c": _seg_sum(state["c"], gid, n).astype(np.int64)}

    def finalize(self, state):
        return state["c"]


class Sum(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        self.name = f"sum({on})"

    def init_state(self, blk, gid, n):
        return {"s": _seg_sum(np.asarray(blk[self.on]), gid, n)}

    def combine(self, state, gid, n):
        return {"s": _seg_sum(state["s"], gid, n)}

    def finalize(self, state):
        return state["s"]


class Mean(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        self.name = f"mean({on})"

    def init_state(self, blk, gid, n):
        return {"s": _seg_sum(np.asarray(blk[self.on]), gid, n),
                "c": np.bincount(gid, minlength=n).astype(np.int64)}

    def combine(self, state, gid, n):
        return {"s": _seg_sum(state["s"], gid, n),
                "c": _seg_sum(state["c"], gid, n).astype(np.int64)}

    def finalize(self, state):
        return state["s"] / np.maximum(state["c"], 1)


class Std(AggregateFn):
    """Population/sample std via (sum, sumsq, count) moments — exact
    merge under re-grouping (reference ``Std`` uses chunked M2 merge;
    moments are the vectorized equivalent at fp64)."""

    def __init__(self, on: str, ddof: int = 1):
        self.on = on
        self.ddof = ddof
        self.name = f"std({on})"

    def init_state(self, blk, gid, n):
        v = np.asarray(blk[self.on], dtype=np.float64)
        return {"s": _seg_sum(v, gid, n),
                "q": _seg_sum(v * v, gid, n),
                "c": np.bincount(gid, minlength=n).astype(np.int64)}

    def combine(self, state, gid, n):
        return {"s": _seg_sum(state["s"], gid, n),
                "q": _seg_sum(state["q"], gid, n),
                "c": _seg_sum(state["c"], gid, n).astype(np.int64)}

    def finalize(self, state):
        c = state["c"].astype(np.float64)
        mean = state["s"] / np.maximum(c, 1)
        var = (state["q"] / np.maximum(c, 1)) - mean * mean
        denom = c - self.ddof
        # count <= ddof → variance undefined → NaN (numpy/pandas do)
        return np.where(
            denom > 0,
            np.sqrt(np.maximum(var * c / np.maximum(denom, 1), 0.0)),
            np.nan)


class _Extremum(AggregateFn):
    _ufunc: np.ufunc
    _kind: str

    def __init__(self, on: str):
        self.on = on
        self.name = f"{self._kind}({on})"

    def _identity(self, dtype: np.dtype):
        if dtype.kind == "f":
            # +/-inf, not finfo.max/min: a column containing infinities
            # must still reduce to them
            return np.inf if self._kind == "min" else -np.inf
        if dtype.kind in "iu":
            lim = np.iinfo(dtype)
            return lim.max if self._kind == "min" else lim.min
        raise TypeError(
            f"{self._kind}() supports numeric columns, got {dtype}")

    def _reduce(self, values: np.ndarray, gid: np.ndarray, n: int,
                counts: Optional[np.ndarray] = None):
        out = np.full(n, self._identity(values.dtype),
                      dtype=values.dtype)
        self._ufunc.at(out, gid, values)
        return out

    def init_state(self, blk, gid, n):
        v = np.asarray(blk[self.on])
        return {"m": self._reduce(v, gid, n),
                "c": np.bincount(gid, minlength=n).astype(np.int64)}

    def combine(self, state, gid, n):
        # groups absent from a partial carry the identity; their count
        # is 0 so the identity never leaks into a real group's result
        mask = state["c"] > 0
        vals = state["m"][mask]
        g = gid[mask]
        out = np.full(n, self._identity(state["m"].dtype),
                      dtype=state["m"].dtype)
        if len(vals):
            self._ufunc.at(out, g, vals)
        return {"m": out,
                "c": _seg_sum(state["c"], gid, n).astype(np.int64)}

    def finalize(self, state):
        return state["m"]


class Min(_Extremum):
    _ufunc = np.minimum
    _kind = "min"


class Max(_Extremum):
    _ufunc = np.maximum
    _kind = "max"
