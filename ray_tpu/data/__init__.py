"""ray_tpu.data — streaming distributed datasets.

Reference: Ray Data (``python/ray/data/``, SURVEY §2.3): a lazy logical
plan of operators executed by a backpressure-aware streaming executor
over blocks in the object store (``_internal/execution/
streaming_executor.py:49``). Here blocks are columnar dicts of numpy
arrays in the shm object store; transforms run as tasks with a bounded
in-flight window; the TPU-shaped addition is double-buffered device
prefetch (``Dataset.iter_device_batches``) feeding jax arrays straight
onto the chips.
"""

from .aggregate import (  # noqa: F401
    AggregateFn,
    Count,
    Max,
    Mean,
    Min,
    Std,
    Sum,
)
from .block import BlockMetadata, block_metadata  # noqa: F401
from .dataset import ActorPoolStrategy, Dataset, GroupedData  # noqa: F401
from .iterator import DataIterator  # noqa: F401
from .read_api import (  # noqa: F401
    from_generators,
    from_items,
    from_numpy,
    range,
    range_tensor,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
    read_tfrecords,
)
from .write_api import install_writers as _install_writers
_install_writers(Dataset)
del _install_writers
from .datasource import (  # noqa: F401
    BinaryDatasource,
    CSVDatasource,
    FileBasedDatasource,
    ImageDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    TextDatasource,
    TFRecordDatasource,
)
