"""Block model: columnar dicts of numpy arrays.

Reference: ``python/ray/data/block.py`` (Arrow-table blocks +
BlockAccessor). Numpy-columnar is the TPU-friendly layout — blocks
convert to jax arrays without a row pivot, and the shm object store
zero-copies numpy.
"""

from __future__ import annotations

from dataclasses import dataclass as _dataclass
from typing import Any, Dict, Iterator, List, Sequence

import numpy as np

Block = Dict[str, np.ndarray]


def block_from_rows(rows: Sequence[Dict[str, Any]]) -> Block:
    if not rows:
        return {}
    cols: Dict[str, List[Any]] = {k: [] for k in rows[0]}
    for row in rows:
        if row.keys() != cols.keys():
            raise ValueError(
                f"inconsistent row keys: {sorted(row)} vs {sorted(cols)}")
        for k, v in row.items():
            cols[k].append(v)
    return {k: np.asarray(v) for k, v in cols.items()}


def block_num_rows(block: Block) -> int:
    for v in block.values():
        return len(v)
    return 0


def block_rows(block: Block) -> Iterator[Dict[str, Any]]:
    n = block_num_rows(block)
    keys = list(block)
    for i in range(n):
        yield {k: block[k][i] for k in keys}


def block_slice(block: Block, start: int, stop: int) -> Block:
    return {k: v[start:stop] for k, v in block.items()}


def block_concat(blocks: Sequence[Block]) -> Block:
    blocks = [b for b in blocks if block_num_rows(b)]
    if not blocks:
        return {}
    keys = list(blocks[0])
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def block_take(block: Block, indices: np.ndarray) -> Block:
    return {k: v[indices] for k, v in block.items()}


def normalize_block(data: Any) -> Block:
    """Accept dict-of-arrays, list-of-rows, or a bare array ('data' col)."""
    if isinstance(data, dict):
        return {k: np.asarray(v) for k, v in data.items()}
    if isinstance(data, np.ndarray):
        return {"data": data}
    if isinstance(data, (list, tuple)):
        if data and isinstance(data[0], dict):
            return block_from_rows(data)
        return {"data": np.asarray(data)}
    raise TypeError(f"cannot interpret {type(data)} as a block")


@_dataclass
class BlockMetadata:
    """Size/shape/schema of one block (reference: ``BlockMetadata`` in
    ``python/ray/data/block.py`` — num_rows/size_bytes/schema)."""

    num_rows: int
    size_bytes: int
    schema: Dict[str, str]          # column -> "dtype shape-tail"


def block_metadata(block: Block) -> BlockMetadata:
    num_rows = block_num_rows(block)
    size = 0
    schema: Dict[str, str] = {}
    for k, v in block.items():
        arr = np.asarray(v)
        if arr.dtype == object:
            size += sum(len(x) if isinstance(x, (bytes, str)) else 64
                        for x in arr.ravel())
            schema[k] = "object"
        else:
            size += arr.nbytes
            tail = arr.shape[1:]
            schema[k] = f"{arr.dtype}{list(tail) if tail else ''}"
    return BlockMetadata(num_rows=num_rows, size_bytes=size, schema=schema)
