"""JobManager: supervised driver-script execution with status + logs.

Reference: ``dashboard/modules/job/job_manager.py:525``. A submitted
entrypoint (a shell command) runs as a supervised subprocess in the head
node's process group with ``RTPU_ADDRESS`` pointing at the cluster, so
the script's ``ray_tpu.init(address=os.environ["RTPU_ADDRESS"])``
attaches as a real driver. Status and metadata live in the GCS KV under
``job:<submission_id>`` (PENDING → RUNNING → SUCCEEDED/FAILED/STOPPED);
stdout+stderr are captured per job and served back through
``get_logs``/REST.

Difference from the reference, on purpose: supervision is a thread in
the head process rather than a detached supervisor actor — one fewer
moving part at this scale; the actor-based form can land once jobs need
to survive head-component restarts.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from .._private import locksan
from .._private import runtime_env as renv


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


class JobManager:
    def __init__(self, gcs, cluster_address: str, session_dir: str):
        self.gcs = gcs
        self.cluster_address = cluster_address
        self.log_dir = os.path.join(session_dir, "job_logs")
        os.makedirs(self.log_dir, exist_ok=True)
        self.session_dir = session_dir
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = locksan.lock("jobs.manager")

    # ------------------------------------------------------------- records
    def _key(self, job_id: str) -> bytes:
        return b"job:" + job_id.encode()

    def _write(self, job_id: str, rec: Dict[str, Any]) -> None:
        self.gcs.kv_put(self._key(job_id), json.dumps(rec).encode())

    def _read(self, job_id: str) -> Optional[Dict[str, Any]]:
        raw = self.gcs.kv_get(self._key(job_id))
        return json.loads(raw) if raw else None

    # ---------------------------------------------------------------- API
    def submit(self, entrypoint: str,
               runtime_env: Optional[dict] = None,
               submission_id: Optional[str] = None,
               metadata: Optional[Dict[str, str]] = None,
               working_dir_zip: Optional[str] = None) -> str:
        job_id = submission_id or f"rtpu-job-{uuid.uuid4().hex[:10]}"
        if self._read(job_id) is not None:
            raise ValueError(f"job {job_id!r} already exists")
        if working_dir_zip:
            # client shipped its working_dir (the head can't see the
            # client's filesystem); unpack and use as the job's cwd
            runtime_env = dict(runtime_env or {})
            runtime_env["working_dir"] = self._unpack_package(
                job_id, working_dir_zip)
        env = renv.validate(runtime_env)
        rec = {
            "job_id": job_id,
            "entrypoint": entrypoint,
            "status": JobStatus.PENDING,
            "start_time": time.time(),
            "end_time": None,
            "return_code": None,
            "message": "",
            "metadata": metadata or {},
        }
        self._write(job_id, rec)
        t = threading.Thread(target=self._supervise,
                             args=(job_id, entrypoint, env),
                             name=f"rtpu-job-{job_id}", daemon=True)
        t.start()
        return job_id

    def _unpack_package(self, job_id: str, b64: str) -> str:
        import base64
        import io
        import zipfile
        target = os.path.join(self.session_dir, "job_pkgs", job_id)
        os.makedirs(target, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(base64.b64decode(b64))) as zf:
            for name in zf.namelist():
                # refuse path traversal out of the package dir
                dest = os.path.realpath(os.path.join(target, name))
                if not dest.startswith(os.path.realpath(target) + os.sep):
                    raise ValueError(f"unsafe path in package: {name!r}")
            zf.extractall(target)
        return target

    def _supervise(self, job_id: str, entrypoint: str,
                   runtime_env: Optional[dict]) -> None:
        rec = self._read(job_id)
        log_path = os.path.join(self.log_dir, f"{job_id}.log")
        try:
            env = dict(os.environ)
            env["RTPU_ADDRESS"] = self.cluster_address
            env["RTPU_JOB_ID"] = job_id
            env["PYTHONUNBUFFERED"] = "1"
            cwd = os.getcwd()
            if runtime_env:
                overrides, env_cwd = renv.stage(runtime_env,
                                                self.session_dir)
                env.update(overrides)
                if env_cwd:
                    cwd = env_cwd
            # the framework itself must stay importable from the job
            # (dev checkouts only; installed builds import anywhere)
            from .._private.config import fw_importable_without_path
            fw_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            pp = env.get("PYTHONPATH", "")
            if (not fw_importable_without_path()
                    and fw_root not in pp.split(os.pathsep)):
                env["PYTHONPATH"] = (pp + os.pathsep if pp else "") + fw_root
            with open(log_path, "ab") as out:
                proc = subprocess.Popen(
                    entrypoint, shell=True, stdout=out,
                    stderr=subprocess.STDOUT, env=env, cwd=cwd,
                    start_new_session=True)    # own group: stop kills all
            with self._lock:
                self._procs[job_id] = proc
            rec["status"] = JobStatus.RUNNING
            self._write(job_id, rec)
            rc = proc.wait()
        except Exception as e:   # noqa: BLE001 — surfaced via the record
            rec["status"] = JobStatus.FAILED
            rec["message"] = f"supervisor error: {e}"
            rec["end_time"] = time.time()
            self._write(job_id, rec)
            return
        with self._lock:
            # finalize under the lock: a concurrent stop() must not
            # overwrite SUCCEEDED/FAILED with STOPPED (or vice versa)
            self._procs.pop(job_id, None)
            current = self._read(job_id) or rec
            if current["status"] in JobStatus.TERMINAL:
                return                   # stop() already finalized it
            current["return_code"] = rc
            current["status"] = (JobStatus.SUCCEEDED if rc == 0
                                 else JobStatus.FAILED)
            current["end_time"] = time.time()
            self._write(job_id, current)

    def stop(self, job_id: str) -> bool:
        with self._lock:
            rec = self._read(job_id)
            if rec is None or rec["status"] in JobStatus.TERMINAL:
                return False
            proc = self._procs.get(job_id)
            rec["status"] = JobStatus.STOPPED
            rec["end_time"] = time.time()
            self._write(job_id, rec)
        if proc is not None:
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except (OSError, ProcessLookupError):
                pass
        return True

    def get_status(self, job_id: str) -> Optional[Dict[str, Any]]:
        return self._read(job_id)

    def get_logs(self, job_id: str, tail_bytes: int = 1 << 20) -> str:
        path = os.path.join(self.log_dir, f"{job_id}.log")
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    def list_jobs(self) -> List[Dict[str, Any]]:
        out = []
        for key in self.gcs.kv_keys(b"job:"):
            raw = self.gcs.kv_get(key)
            if raw:
                out.append(json.loads(raw))
        return sorted(out, key=lambda r: r.get("start_time") or 0)
