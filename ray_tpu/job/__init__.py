"""Job submission: run driver scripts against a cluster from outside it.

Reference: ``dashboard/modules/job/job_manager.py:525`` (job lifecycle),
``job_head.py`` (REST API), ``python/ray/dashboard/modules/job/sdk.py``
(JobSubmissionClient) and ``ray job submit`` CLI.
"""

from .client import JobSubmissionClient  # noqa: F401
from .manager import JobManager, JobStatus  # noqa: F401
