"""REST front for the JobManager.

Reference: ``dashboard/modules/job/job_head.py`` — the same endpoint
shapes on the head node:

    POST /api/jobs/            {entrypoint, runtime_env?, submission_id?}
    GET  /api/jobs/            list
    GET  /api/jobs/<id>        status record
    GET  /api/jobs/<id>/logs   {"logs": "..."}
    POST /api/jobs/<id>/stop   {"stopped": bool}
"""

from __future__ import annotations

from .._private.http_util import HttpServerBase, JsonHandler
from .manager import JobManager


class _Handler(JsonHandler):
    manager: JobManager = None   # set by server factory

    def do_POST(self):
        parts = [p for p in self.path.split("/") if p]
        try:
            if parts[:2] == ["api", "jobs"] and len(parts) == 2:
                req = self._body()
                job_id = self.manager.submit(
                    entrypoint=req["entrypoint"],
                    runtime_env=req.get("runtime_env"),
                    submission_id=req.get("submission_id"),
                    metadata=req.get("metadata"),
                    working_dir_zip=req.get("working_dir_zip"))
                self._json(200, {"job_id": job_id})
            elif (parts[:2] == ["api", "jobs"] and len(parts) == 4
                  and parts[3] == "stop"):
                self._json(200, {"stopped": self.manager.stop(parts[2])})
            else:
                self._json(404, {"error": f"no route {self.path}"})
        except ValueError as e:
            self._json(400, {"error": str(e)})
        except Exception as e:   # noqa: BLE001 — API surface
            self._json(500, {"error": str(e)})

    def do_GET(self):
        parts = [p for p in self.path.split("/") if p]
        try:
            if parts[:2] == ["api", "jobs"] and len(parts) == 2:
                self._json(200, {"jobs": self.manager.list_jobs()})
            elif parts[:2] == ["api", "jobs"] and len(parts) == 3:
                rec = self.manager.get_status(parts[2])
                if rec is None:
                    self._json(404, {"error": f"no job {parts[2]}"})
                else:
                    self._json(200, rec)
            elif (parts[:2] == ["api", "jobs"] and len(parts) == 4
                  and parts[3] == "logs"):
                self._json(200, {"logs": self.manager.get_logs(parts[2])})
            else:
                self._json(404, {"error": f"no route {self.path}"})
        except Exception as e:   # noqa: BLE001 — API surface
            self._json(500, {"error": str(e)})


class JobRestServer(HttpServerBase):
    thread_name = "rtpu-job-rest"

    # loopback by default: the REST API exposes job submission (arbitrary
    # code execution) — binding all interfaces requires an explicit opt-in
    # (reference dashboard defaults to 127.0.0.1 for the same reason)
    def __init__(self, manager: JobManager, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__(_Handler, host=host, port=port, manager=manager)
