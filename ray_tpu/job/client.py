"""JobSubmissionClient: talk to the job REST API from anywhere.

Reference: ``python/ray/dashboard/modules/job/sdk.py``
(JobSubmissionClient.submit_job / get_job_status / get_job_logs).
"""

from __future__ import annotations

import base64
import io
import json
import os
import time
import urllib.error
import urllib.request
import zipfile
from typing import Any, Dict, List, Optional

from .manager import JobStatus


class JobSubmissionError(RuntimeError):
    pass


class JobSubmissionClient:
    def __init__(self, address: str):
        """``address`` is ``host:port`` of the head's job REST server
        (or a full ``http://...`` URL)."""
        if not address.startswith("http"):
            address = f"http://{address}"
        self._base = address.rstrip("/")

    def _call(self, method: str, path: str,
              payload: Optional[dict] = None) -> Dict[str, Any]:
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            self._base + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            # surface the server's JSON error body, not a bare traceback
            try:
                message = json.loads(e.read()).get("error", str(e))
            except Exception:
                message = str(e)
            raise JobSubmissionError(
                f"{method} {path} failed ({e.code}): {message}") from None

    @staticmethod
    def _package_dir(path: str, max_bytes: int = 200 << 20) -> str:
        """Zip a client-side working_dir so it ships with the request —
        the head cannot see the client's filesystem (reference: zip to
        GCS, ``packaging.py``)."""
        buf = io.BytesIO()
        total = 0
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for root, _, files in os.walk(path):
                for name in files:
                    full = os.path.join(root, name)
                    total += os.path.getsize(full)
                    if total > max_bytes:
                        raise ValueError(
                            f"working_dir exceeds {max_bytes >> 20}MB")
                    zf.write(full, os.path.relpath(full, path))
        return base64.b64encode(buf.getvalue()).decode()

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        payload = {
            "entrypoint": entrypoint, "runtime_env": runtime_env,
            "submission_id": submission_id, "metadata": metadata,
        }
        wd = (runtime_env or {}).get("working_dir")
        if wd and os.path.isdir(wd):
            env = dict(runtime_env)
            del env["working_dir"]
            payload["runtime_env"] = env or None
            payload["working_dir_zip"] = self._package_dir(wd)
        return self._call("POST", "/api/jobs/", payload)["job_id"]

    def get_job_status(self, job_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/api/jobs/{job_id}")

    def get_job_logs(self, job_id: str) -> str:
        return self._call("GET", f"/api/jobs/{job_id}/logs")["logs"]

    def stop_job(self, job_id: str) -> bool:
        return self._call("POST", f"/api/jobs/{job_id}/stop")["stopped"]

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._call("GET", "/api/jobs/")["jobs"]

    def wait_until_finished(self, job_id: str,
                            timeout: float = 300.0) -> Dict[str, Any]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            rec = self.get_job_status(job_id)
            if rec["status"] in JobStatus.TERMINAL:
                return rec
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")
