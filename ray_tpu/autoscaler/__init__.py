"""Autoscaler: resize the cluster to match queued resource demand.

Reference: ``python/ray/autoscaler/_private/autoscaler.py:171``
(StandardAutoscaler) + ``resource_demand_scheduler.py:102`` (bin-pack
demand onto node types) + ``fake_multi_node/node_provider.py:237``
(cloudless provider for tests).
"""

from .autoscaler import AutoscalerConfig, NodeType, StandardAutoscaler  # noqa: F401
from .node_provider import FakeNodeProvider, NodeProvider  # noqa: F401
