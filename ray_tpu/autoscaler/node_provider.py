"""Node providers: how the autoscaler actually adds/removes machines.

Reference: ``python/ray/autoscaler/node_provider.py`` (NodeProvider
interface) and ``_private/fake_multi_node/node_provider.py:237``
(FakeMultiNodeProvider — cloudless nodes for tests). The fake provider
here backs onto ``cluster_utils.Cluster``, so scale-up creates a REAL
node service (scheduler, worker pool, object store) and scale-down
kills one.
"""

from __future__ import annotations


from .._private import locksan
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Minimal provider surface the autoscaler drives."""

    def create_node(self, node_type: str,
                    resources: Dict[str, float],
                    labels: Dict[str, str]) -> Any:
        """Launch one node of ``node_type``; returns a provider handle."""
        raise NotImplementedError

    def terminate_node(self, handle: Any) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[Any]:
        raise NotImplementedError

    def node_id_of(self, handle: Any):
        """The cluster NodeID a provider handle registered as."""
        raise NotImplementedError

    def node_type_of(self, handle: Any) -> str:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Adds/removes real in-process node services on one machine."""

    def __init__(self, cluster):
        self._cluster = cluster
        self._lock = locksan.lock("autoscaler.provider")
        self._nodes: List[dict] = []   # {"node": ..., "type": str}

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> Any:
        node = self._cluster.add_node(
            resources=dict(resources),
            labels={**labels, "rtpu.io/node-type": node_type})
        rec = {"node": node, "type": node_type}
        with self._lock:
            self._nodes.append(rec)
        return rec

    def terminate_node(self, handle: Any) -> None:
        # remove from the cluster FIRST: if that raises, the handle stays
        # tracked and the autoscaler retries next update
        self._cluster.remove_node(handle["node"], allow_graceful=True)
        with self._lock:
            if handle in self._nodes:
                self._nodes.remove(handle)

    def non_terminated_nodes(self) -> List[Any]:
        with self._lock:
            return list(self._nodes)

    def node_id_of(self, handle: Any):
        node = handle["node"]
        nid = getattr(node, "node_id", None)
        if nid is not None:
            return nid
        from .._private.ids import NodeID
        return NodeID.from_hex(node.node_id_hex)   # process-isolated node

    def node_type_of(self, handle: Any) -> str:
        return handle["type"]
