"""StandardAutoscaler: demand-driven scale-up, idle-driven scale-down.

Reference: ``python/ray/autoscaler/_private/autoscaler.py:171``. Each
update:

1. read the cluster's load report from the control plane — every node's
   heartbeat carries its availability and its queued-but-unplaced
   resource shapes (``NodeService.pending_demand``);
2. subtract what the live cluster can already absorb, then first-fit
   bin-pack the unmet shapes onto fresh nodes of the configured node
   types (``resource_demand_scheduler.py:102``), bounded by per-type
   ``max_workers`` and ``upscaling_speed``;
3. terminate provider nodes that have been fully idle (nothing running,
   nothing queued) longer than ``idle_timeout_s``, down to per-type
   ``min_workers``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class NodeType:
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeType] = field(default_factory=dict)
    idle_timeout_s: float = 60.0
    update_interval_s: float = 5.0
    # max fraction of the current node count added per update (>=1 node)
    upscaling_speed: float = 1.0


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in demand.items() if v > 0)


def _subtract(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


class StandardAutoscaler:
    def __init__(self, gcs, provider, config: AutoscalerConfig):
        self.gcs = gcs
        self.provider = provider
        self.config = config
        self._idle_since: Dict[str, float] = {}    # provider handle id(str)
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # observability (and test hooks)
        self.num_launched = 0
        self.num_terminated = 0

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rtpu-autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _run(self) -> None:
        while not self._stopped.wait(self.config.update_interval_s):
            try:
                self.update()
            except Exception:
                import sys
                import traceback
                traceback.print_exc(file=sys.stderr)

    # max age of a pending-PG record before it stops driving scale-up
    # (the blocked client refreshes it every <=0.5s when healthy; the
    # margin is wide because a refresh that slips under host load must
    # not drop the gang mid-launch — that drained half-launched gang
    # nodes and churned the whole placement. A dead driver's record
    # still expires, just later.)
    PENDING_PG_STALE_S = 30.0

    # --------------------------------------------------------------- update
    def update(self) -> None:
        nodes = [n for n in self.gcs.alive_nodes()]
        demand: List[Dict[str, float]] = []
        for n in nodes:
            demand.extend(n.pending_shapes)
        gangs = self._pending_gangs()
        self._scale_up(nodes, demand, gangs)
        self._scale_down(nodes, demand or gangs)

    def _pending_gangs(self) -> List[Any]:
        """Fresh unplaceable placement groups (reference:
        ``resource_demand_scheduler.py:102`` — pending PGs feed
        scale-up; on TPU, gangs are THE autoscaling driver)."""
        try:
            recs = self.gcs.pending_pgs_snapshot()
        except Exception:
            return []
        now = time.time()
        return [r["spec"] for r in recs
                if now - r["last_attempt"] < self.PENDING_PG_STALE_S]

    def _scale_up(self, nodes,
                  demand: List[Dict[str, float]],
                  gangs: Optional[List[Any]] = None) -> None:
        if not demand and not gangs:
            return
        # shapes the live cluster will absorb on its own don't count
        avail = [dict(n.resources_available or n.resources_total)
                 for n in nodes]
        unmet = []
        for shape in demand:
            if not shape:
                continue
            placed = False
            for a in avail:
                if _fits(a, shape):
                    _subtract(a, shape)
                    placed = True
                    break
            if not placed:
                unmet.append(shape)

        counts = self._count_by_type()
        # first-fit decreasing over open bins of configured node types
        bins: List[tuple] = []                     # (type_name, remaining)
        to_launch: Dict[str, int] = {}

        def open_bin(shape) -> bool:
            for tname, ntype in self.config.node_types.items():
                live = counts.get(tname, 0) + to_launch.get(tname, 0)
                if live >= ntype.max_workers:
                    continue
                if _fits(dict(ntype.resources), shape):
                    remaining = dict(ntype.resources)
                    _subtract(remaining, shape)
                    bins.append((tname, remaining))
                    to_launch[tname] = to_launch.get(tname, 0) + 1
                    return True
            return False

        for shape in sorted(unmet, key=lambda s: -sum(s.values())):
            placed = False
            for _, remaining in bins:
                if _fits(remaining, shape):
                    _subtract(remaining, shape)
                    placed = True
                    break
            if not placed:
                open_bin(shape)
            # no type fits the shape: it stays unmet (the task will fail
            # at its grace deadline with a clear error)

        # Gangs: pack WHOLE placement groups, honoring their strategy —
        # partial capacity is useless to a gang, so the nodes it needs
        # are planned together (atomic scale-up; the launch-cap below
        # still rate-limits the provider calls per update).
        for spec in gangs or []:
            self._plan_gang(spec, avail, bins, counts, to_launch, open_bin)

        cap = max(1, int(self.config.upscaling_speed * max(1, len(nodes))))
        budget = cap
        for tname, n in to_launch.items():
            n = min(n, budget)
            budget -= n
            ntype = self.config.node_types[tname]
            for _ in range(n):
                self.provider.create_node(
                    tname, ntype.resources,
                    labels={"rtpu.io/autoscaled": "1"})
                self.num_launched += 1

    def _plan_gang(self, spec, avail, bins, counts, to_launch,
                   open_bin) -> None:
        """Plan nodes for one unplaceable placement group.

        STRICT_PACK: all bundles on ONE node — a single new node fitting
        their sum. STRICT_SPREAD: each bundle on a DISTINCT node — one
        new node per bundle not absorbable by a distinct live node.
        PACK/SPREAD: best-effort — bundles bin-packed like plain shapes.
        Reference: ``resource_demand_scheduler.py:102`` +
        ``bundle_scheduling_policy.cc`` strategy semantics.
        """
        bundles = list(spec.bundles)
        if spec.strategy == "STRICT_PACK":
            total: Dict[str, float] = {}
            for b in bundles:
                for k, v in b.items():
                    total[k] = total.get(k, 0.0) + v
            for a in avail:
                if _fits(a, total):
                    _subtract(a, total)     # live capacity will absorb it
                    return
            open_bin(total)
            return
        if spec.strategy == "STRICT_SPREAD":
            # greedily absorb bundles onto DISTINCT live nodes; a node
            # may host at most one bundle of this gang
            used = set()
            remaining = []
            for b in bundles:
                for i, a in enumerate(avail):
                    if i not in used and _fits(a, b):
                        _subtract(a, b)
                        used.add(i)
                        break
                else:
                    remaining.append(b)
            # one FRESH node per leftover bundle (bins opened by other
            # demand must not double-host two bundles of this gang)
            for b in remaining:
                open_bin(b)
            return
        # PACK / SPREAD: best-effort placement, plain bin-packing
        for b in bundles:
            placed = False
            for a in avail:
                if _fits(a, b):
                    _subtract(a, b)
                    placed = True
                    break
            if placed:
                continue
            for _, remaining_bin in bins:
                if _fits(remaining_bin, b):
                    _subtract(remaining_bin, b)
                    placed = True
                    break
            if not placed:
                open_bin(b)

    def _scale_down(self, nodes, demand: List[Dict[str, float]]) -> None:
        if demand:
            # queued work anywhere: keep capacity (conservative, like the
            # reference's load-based idle criterion)
            self._idle_since.clear()
            return
        counts = self._count_by_type()
        by_id = {n.node_id: n for n in nodes}
        # nodes holding the primary copy of a shm/arena-backed object are
        # not drainable — terminating them would vaporize data a driver
        # may still get() (put objects have no lineage to rebuild from).
        # Inline values travel in the directory meta itself and survive
        # their host.
        try:
            object_hosts = {nid for _, (nid, meta) in
                            self.gcs.directory_snapshot()
                            if meta.shm_name is not None
                            or meta.arena_ref is not None}
        except Exception:
            object_hosts = set()
        try:
            # reservation state is authoritative at the GCS: a freshly
            # reserved gang node can look idle until its next heartbeat
            # lands, but must never drain while its PG lives
            gang_hosts = self.gcs.gang_hosts()
        except Exception:
            gang_hosts = set()
        now = time.monotonic()
        for handle in self.provider.non_terminated_nodes():
            node_id = self.provider.node_id_of(handle)
            key = node_id.hex()
            info = by_id.get(node_id)
            if info is None:
                continue
            avail = info.resources_available or {}
            busy = any(total - avail.get(k, 0.0) > 1e-9
                       for k, total in info.resources_total.items())
            if (busy or info.pending_shapes or node_id in object_hosts
                    or node_id in gang_hosts):
                self._idle_since.pop(key, None)
                continue
            first = self._idle_since.setdefault(key, now)
            if now - first < self.config.idle_timeout_s:
                continue
            tname = self.provider.node_type_of(handle)
            ntype = self.config.node_types.get(tname)
            if ntype is not None and counts.get(tname, 0) <= ntype.min_workers:
                continue
            self.provider.terminate_node(handle)
            counts[tname] = counts.get(tname, 0) - 1
            self._idle_since.pop(key, None)
            self.num_terminated += 1

    def _count_by_type(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for handle in self.provider.non_terminated_nodes():
            t = self.provider.node_type_of(handle)
            counts[t] = counts.get(t, 0) + 1
        return counts
