"""ray_tpu.state — queryable cluster state (the state API).

Reference: ``python/ray/experimental/state/api.py`` (list/get/summarize
for tasks, actors, objects, nodes, placement groups) backed by
``GcsTaskManager``; here the node's STATE_QUERY RPC serves the same
records straight from the control plane.
"""

from .api import (  # noqa: F401
    build_health_report,
    cluster_stacks,
    collective_health,
    events_stats,
    flight_records,
    health_report,
    list_actors,
    list_cluster_events,
    list_events,
    list_jobs,
    list_lifecycle_events,
    list_metrics,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    list_workers,
    memory_summary,
    metrics_history,
    metrics_trends,
    profile,
    serve_health,
    serve_requests,
    summarize_actors,
    summarize_metrics,
    summarize_tasks,
    timeline,
)
