"""State API implementation.

Reference surface: ``experimental/state/api.py`` list_* / summarize_*
and ``ray.timeline()`` (``_private/state.py:865`` — Chrome trace JSON
from task events).
"""

from __future__ import annotations

import json
from collections import Counter, defaultdict
from typing import Any, Dict, List, Optional

from .._private import context as _ctx


def _query(what: str, filters: Optional[dict] = None) -> Any:
    return _ctx.require_client().state_query(what, filters)


def _apply_filters(rows: List[dict], filters: Optional[dict]) -> List[dict]:
    if not filters:
        return rows
    out = []
    for row in rows:
        if all(str(row.get(k)) == str(v) for k, v in filters.items()):
            out.append(row)
    return out


def _hex(v) -> str:
    return v.hex() if hasattr(v, "hex") else str(v)


# Row shaping is shared with the dashboard, which reads the same raw
# records directly from the head's GCS (no client in that process).

def shape_tasks(events: List[dict]) -> List[dict]:
    latest: Dict[Any, dict] = {}
    for ev in events or []:
        latest[ev["task_id"]] = {
            "task_id": _hex(ev["task_id"]),
            "name": ev["name"],
            "state": ev["state"],
            "node_id": _hex(ev["node_id"]),
            "is_actor_task": ev.get("is_actor_task", False),
            "timestamp": ev["timestamp"],
        }
    return sorted(latest.values(), key=lambda r: r["timestamp"])


def shape_actors(recs: List[dict]) -> List[dict]:
    return [{
        "actor_id": _hex(rec["actor_id"]),
        "class_name": rec["class_name"],
        "name": rec.get("name"),
        "state": rec["state"],
        "num_restarts": rec.get("num_restarts", 0),
        "max_restarts": rec.get("max_restarts", 0),
    } for rec in recs or []]


def shape_objects(recs: List[dict]) -> List[dict]:
    """Tolerant of records missing optional keys (a ledger row for an
    object that is held but not yet sealed has no node/size; pre-PR
    minimal records shape fine too)."""
    return [{
        "object_id": _hex(rec.get("object_id")),
        "node_id": (_hex(rec["node_id"])
                    if rec.get("node_id") is not None else None),
        "size": rec.get("size"),
        "callsite": rec.get("callsite"),
        "creator": rec.get("creator"),
        "ref_types": dict(rec.get("ref_types") or {}),
        "pins": rec.get("pins", 0),
        "pinned_in_store": rec.get("pinned_in_store", 0),
        "spilled": rec.get("spilled", False),
        "leaked": rec.get("leaked", False),
    } for rec in recs or []]


def shape_leaks(recs: List[dict]) -> List[dict]:
    return [{
        **rec,
        "object_id": _hex(rec.get("object_id")),
        "node_id": (_hex(rec["node_id"])
                    if rec.get("node_id") is not None else None),
    } for rec in recs or []]


def summarize_memory_rows(rows: List[dict], group_by: str = "callsite",
                          top_k: int = 20,
                          sort_by: str = "bytes") -> Dict[str, Any]:
    """Group shaped object rows by creation callsite / creator / node
    with byte+count totals and a merged ref-type breakdown, largest
    group first by ``sort_by`` (``bytes`` | ``count`` — applied BEFORE
    the top-K cut, so the #1 group by the chosen key is always shown).
    The ``ray memory --group-by`` rollup, shared by
    ``memory_summary()``, the dashboard ``/api/memory`` endpoint and
    ``rtpu memory``."""
    key_field = "node_id" if group_by == "node" else group_by
    if key_field not in ("callsite", "creator", "node_id"):
        raise ValueError(f"unknown group_by {group_by!r} "
                         "(callsite | creator | node)")
    if sort_by not in ("bytes", "count"):
        raise ValueError(f"unknown sort_by {sort_by!r} (bytes | count)")
    groups: Dict[str, dict] = {}
    total_bytes = 0
    for r in rows:
        size = r.get("size") or 0
        total_bytes += size
        key = str(r.get(key_field) or "<unknown>")
        g = groups.setdefault(key, {"key": key, "objects": 0,
                                    "bytes": 0, "ref_types": {}})
        g["objects"] += 1
        g["bytes"] += size
        for t, n in (r.get("ref_types") or {}).items():
            g["ref_types"][t] = g["ref_types"].get(t, 0) + n
    sort_key = ((lambda g: (-g["objects"], -g["bytes"], g["key"]))
                if sort_by == "count" else
                (lambda g: (-g["bytes"], -g["objects"], g["key"])))
    ordered = sorted(groups.values(), key=sort_key)
    return {
        "group_by": group_by,
        "sort_by": sort_by,
        "total_objects": len(rows),
        "total_bytes": total_bytes,
        "groups": ordered[:top_k],
        "dropped_groups": max(0, len(ordered) - top_k),
    }


def shape_placement_groups(recs: List[dict]) -> List[dict]:
    return [{
        "pg_id": _hex(rec["pg_id"]),
        "state": rec.get("state"),
        "bundles": rec["bundles"],
        "strategy": rec["strategy"],
    } for rec in recs or []]


def shape_nodes(recs: List[dict]) -> List[dict]:
    return [{**rec, "node_id": _hex(rec["node_id"])} for rec in recs or []]


def shape_metrics(snap: Optional[dict]) -> List[dict]:
    """Flatten a telemetry snapshot (tuple-keyed tables) into JSON-able
    series rows, shared by the dashboard ``/api/metrics`` endpoint and
    ``summarize_metrics``."""
    snap = snap or {}
    meta = snap.get("meta") or {}
    rows: List[dict] = []

    def base(name: str, tags: tuple) -> dict:
        m = meta.get(name) or {}
        return {"name": name, "kind": m.get("kind"),
                "description": m.get("description") or "",
                "tags": dict(tags)}

    for (name, tags), value in (snap.get("counters") or {}).items():
        rows.append({**base(name, tags), "kind": "counter",
                     "value": value})
    for (name, tags), (value, ts) in (snap.get("gauges") or {}).items():
        rows.append({**base(name, tags), "kind": "gauge", "value": value,
                     "timestamp": ts})
    for (name, tags), h in (snap.get("hists") or {}).items():
        buckets = list(h.get("buckets") or ())
        counts = list(h.get("counts") or ())
        cumulative, cum = [], 0
        for i, b in enumerate(buckets):
            cum += counts[i] if i < len(counts) else 0
            cumulative.append([b, cum])
        rows.append({**base(name, tags), "kind": "histogram",
                     "buckets": cumulative,
                     "sum": h.get("sum", 0.0),
                     "count": h.get("count", 0),
                     "exemplar": h.get("exemplar")})
    from .._private import telemetry as _tm
    for (name, tags), d in (snap.get("digests") or {}).items():
        rows.append({**base(name, tags), "kind": "digest",
                     "sum": d.get("sum", 0.0),
                     "count": d.get("count", 0),
                     "min": d.get("min"), "max": d.get("max"),
                     "quantiles": {
                         "p50": _tm.digest_quantile(d, 0.50),
                         "p90": _tm.digest_quantile(d, 0.90),
                         "p95": _tm.digest_quantile(d, 0.95),
                         "p99": _tm.digest_quantile(d, 0.99)}})
    rows.sort(key=lambda r: (r["name"], sorted(r["tags"].items())))
    return rows


def list_tasks(filters: Optional[dict] = None,
               limit: int = 1000) -> List[dict]:
    """Task state transitions (latest state per task)."""
    rows = shape_tasks(_query("tasks"))
    return _apply_filters(rows, filters)[:limit]


def list_actors(filters: Optional[dict] = None,
                limit: int = 1000) -> List[dict]:
    return _apply_filters(shape_actors(_query("actors")), filters)[:limit]


def list_objects(filters: Optional[dict] = None,
                 limit: int = 1000) -> List[dict]:
    return _apply_filters(shape_objects(_query("objects")), filters)[:limit]


def list_placement_groups(filters: Optional[dict] = None,
                          limit: int = 1000) -> List[dict]:
    return _apply_filters(
        shape_placement_groups(_query("placement_groups")), filters)[:limit]


def list_nodes(filters: Optional[dict] = None) -> List[dict]:
    return _apply_filters(_ctx.require_client().cluster_info("nodes") or [],
                          filters)


def list_workers(filters: Optional[dict] = None) -> List[dict]:
    return _apply_filters(
        _ctx.require_client().cluster_info("workers") or [], filters)


def list_jobs(filters: Optional[dict] = None) -> List[dict]:
    """Driver jobs with start/end times (reference: ``ray list jobs``)."""
    rows = [{**rec, "job_id": _hex(rec["job_id"])}
            for rec in _query("jobs") or []]
    return _apply_filters(rows, filters)


def summarize_task_rows(rows: List[dict]) -> Dict[str, Any]:
    by_state = Counter(r["state"] for r in rows)
    by_func: Dict[str, Counter] = defaultdict(Counter)
    for r in rows:
        by_func[r["name"]][r["state"]] += 1
    return {"total": len(rows), "by_state": dict(by_state),
            "by_func": {k: dict(v) for k, v in by_func.items()}}


def summarize_actor_rows(rows: List[dict]) -> Dict[str, Any]:
    by_state = Counter(r["state"] for r in rows)
    by_class: Dict[str, Counter] = defaultdict(Counter)
    for r in rows:
        by_class[r["class_name"]][r["state"]] += 1
    return {"total": len(rows), "by_state": dict(by_state),
            "by_class": {k: dict(v) for k, v in by_class.items()}}


def list_metrics(filters: Optional[dict] = None,
                 limit: int = 10000) -> List[dict]:
    """Cluster-wide runtime + user metric series (merged telemetry
    table on the control plane)."""
    rows = shape_metrics(_query("metrics"))
    if filters:
        name = filters.get("name")
        if name is not None:
            rows = [r for r in rows if r["name"] == name]
        rows = [r for r in rows
                if all(str(r["tags"].get(k)) == str(v)
                       for k, v in filters.items() if k != "name")]
    return rows[:limit]


def summarize_metrics(snap: Optional[dict] = None) -> Dict[str, Any]:
    """Per-metric rollup: series count plus a kind-appropriate total
    (counter sum, latest gauge values, histogram count/mean) — the
    ``ray summary``-style view of the telemetry table. Pass ``snap``
    to roll up an already-fetched snapshot (health_report fetches the
    cluster table once and shares it across its sections)."""
    return summarize_metric_rows(shape_metrics(
        snap if snap is not None else _query("metrics")))


def summarize_metric_rows(rows: List[dict]) -> Dict[str, Any]:
    """Pure row-based half of ``summarize_metrics`` — shared with the
    offline bundle replay (``rtpu autopsy``), whose metrics arrive as
    the JSON rows a bundle stores, not a live tuple-keyed snapshot."""
    out: Dict[str, Any] = {}
    for row in rows or []:
        ent = out.setdefault(row["name"], {
            "kind": row["kind"], "description": row["description"],
            "series": 0})
        ent["series"] += 1
        if row["kind"] == "counter":
            ent["total"] = ent.get("total", 0.0) + row["value"]
        elif row["kind"] == "gauge":
            ent["last"] = row["value"]
        else:   # histogram and digest rows both carry count/sum
            ent["count"] = ent.get("count", 0) + row["count"]
            ent["sum"] = ent.get("sum", 0.0) + row["sum"]
            if ent["count"]:
                ent["mean"] = ent["sum"] / ent["count"]
            if row["kind"] == "digest" and row.get("quantiles"):
                # quantiles don't aggregate across tag-sets: keep them
                # only while the name has ONE series — pairing a merged
                # count with one series' percentiles would mislead
                # (use serve_health / list_metrics for per-tag views)
                if ent["series"] == 1:
                    ent["quantiles"] = row["quantiles"]
                else:
                    ent.pop("quantiles", None)
    return out


def memory_summary(group_by: str = "callsite", top_k: int = 20,
                   sort_by: str = "bytes") -> Dict[str, Any]:
    """Cluster-wide object-memory rollup (reference: ``ray memory`` /
    memory summary): every object the control plane tracks — with its
    creation callsite, creator task/actor and reference types
    (LOCAL_REFERENCE / USED_BY_PENDING_TASK / CAPTURED_IN_OBJECT /
    ACTOR_HANDLE / PINNED_IN_STORE) — grouped by ``group_by``
    (``callsite`` | ``creator`` | ``node``) with byte totals, plus the
    current leak findings and per-node store stats."""
    mem = _query("memory") or {}
    rows = shape_objects(mem.get("objects"))
    out = summarize_memory_rows(rows, group_by=group_by, top_k=top_k,
                                sort_by=sort_by)
    out["leaks"] = shape_leaks(mem.get("leaks"))
    out["stores"] = mem.get("stores") or {}
    return out


def shape_serve_health(snap: Optional[dict]) -> Dict[str, Any]:
    """Per-deployment serving health from one merged metrics snapshot —
    the exact tuple the autoscaler consumes: latency / queue-wait /
    batch-size percentiles (streaming digests), live queue depth, a
    per-replica table, and request/error totals. Shared by
    ``state.serve_health()``, the dashboard ``GET /api/serve`` (which
    reads the head's table with no client) and ``rtpu serve-status``."""
    return serve_health_from_rows(shape_metrics(snap))


def serve_health_from_rows(rows: List[dict]) -> Dict[str, Any]:
    """Row-based half of ``shape_serve_health``: consumes the JSON
    series rows ``shape_metrics`` produces — which is exactly what a
    debug bundle stores, so ``rtpu autopsy`` replays the serve surface
    offline through this same function."""
    deps: Dict[str, dict] = {}

    def ent(name: str) -> dict:
        d = deps.get(name)
        if d is None:
            d = deps[name] = {
                "deployment": name, "requests_total": 0.0,
                "errors_total": 0.0, "error_rate": 0.0,
                "queue_depth": 0.0, "replicas": [],
                "latency": {}, "queue_wait": {}, "batch_size": {},
            }
        return d

    digest_fields = {
        "rtpu_serve_request_latency_digest_seconds": "latency",
        "rtpu_serve_queue_wait_digest_seconds": "queue_wait",
        "rtpu_serve_batch_size_digest": "batch_size",
    }
    for row in rows or []:
        name, t = row.get("name"), row.get("tags") or {}
        if name == "rtpu_serve_requests_total":
            d = ent(t.get("deployment", "default"))
            d["requests_total"] += row.get("value") or 0.0
            if t.get("status") == "error":
                d["errors_total"] += row.get("value") or 0.0
        elif name == "rtpu_serve_replica_queue_depth":
            value = row.get("value")
            if value is None or value != value or value < 0:
                continue    # in-flight delete marker / defensive
            d = ent(t.get("deployment", "default"))
            d["queue_depth"] += value
            d["replicas"].append({"replica": t.get("replica", "0"),
                                  "queue_depth": value})
        elif name in digest_fields:
            q = row.get("quantiles") or {}
            count = row.get("count") or 0
            ent(t.get("deployment", "default"))[digest_fields[name]] = {
                "p50": q.get("p50", 0.0),
                "p95": q.get("p95", 0.0),
                "p99": q.get("p99", 0.0),
                "count": count,
                "mean": ((row.get("sum") or 0.0) / count if count
                         else 0.0),
                "max": row.get("max"),
            }
    worst = None
    for d in deps.values():
        d["replicas"].sort(key=lambda r: r["replica"])
        if d["requests_total"]:
            d["error_rate"] = d["errors_total"] / d["requests_total"]
        # worst = highest error rate, then highest p99 latency — the
        # deployment the doctor names first
        key = (d["error_rate"], (d["latency"] or {}).get("p99", 0.0))
        if worst is None or key > worst[0]:
            worst = (key, d["deployment"])
    return {"deployments": deps,
            "worst": worst[1] if worst else None}


def shape_serve_trends(history_result: dict) -> Dict[str, Any]:
    """Per-deployment movement over one windowed history query — the
    exact ``trend=`` signal ROADMAP item 5's autoscaler consumes:
    queue-depth head/tail means (summed over replicas), latency and
    queue-wait p95 head/tail, and request rate head/tail. Pure (history
    rows in, dict out) so the live ``serve_health(trend=)`` and the
    offline autopsy share it."""
    from .._private import history as _h
    window = round(float(history_result.get("window_s") or 0.0))
    out: Dict[str, dict] = {}

    def ent(dep: str) -> dict:
        d = out.get(dep)
        if d is None:
            d = out[dep] = {"deployment": dep, "window_s": window}
        return d

    def pair(head: float, tail: float) -> dict:
        return {"head": round(head, 5), "tail": round(tail, 5),
                "ratio": round(tail / head, 2) if head > 0 else None}

    queue: Dict[str, List[float]] = {}
    rate: Dict[str, List[float]] = {}
    for s in history_result.get("series") or []:
        name, tags = s["name"], s["tags"]
        dep = tags.get("deployment")
        if dep is None:
            continue
        if name == "rtpu_serve_replica_queue_depth":
            h, t = _h._head_tail(_h.shape_points(s["points"], "value"))
            queue.setdefault(dep, [0.0, 0.0])
            queue[dep][0] += h
            queue[dep][1] += t
        elif name == "rtpu_serve_requests_total":
            h, t = _h._head_tail(_h.shape_points(s["points"], "rate"))
            rate.setdefault(dep, [0.0, 0.0])
            rate[dep][0] += h
            rate[dep][1] += t
        elif name == "rtpu_serve_request_latency_digest_seconds":
            pts = [[ts, v.get("p95", 0.0)] for ts, v in s["points"]
                   if isinstance(v, dict) and v.get("count")]
            h, t = _h._head_tail(pts)
            ent(dep)["latency_p95"] = pair(h, t)
        elif name == "rtpu_serve_queue_wait_digest_seconds":
            pts = [[ts, v.get("p95", 0.0)] for ts, v in s["points"]
                   if isinstance(v, dict) and v.get("count")]
            h, t = _h._head_tail(pts)
            ent(dep)["queue_wait_p95"] = pair(h, t)
    for dep, (h, t) in queue.items():
        ent(dep)["queue_depth"] = pair(h, t)
    for dep, (h, t) in rate.items():
        ent(dep)["request_rate"] = pair(h, t)
    return out


def serve_health(trend: Optional[float] = None) -> Dict[str, Any]:
    """Cluster-wide serving health: per-deployment latency/queue-wait/
    batch-size percentiles (from the streaming digests), queue depth,
    error rate and the replica table (see ``shape_serve_health``).
    ``trend=<seconds>`` additionally attaches per-deployment head/tail
    movement over that retention window (queue depth, latency p95,
    queue-wait p95, request rate) — the autoscaling signal with a time
    axis."""
    base = shape_serve_health(_query("metrics"))
    if trend:
        try:
            hist = _query("metrics_history",
                          {"window": float(trend)}) or {}
        except Exception:   # noqa: BLE001 — trends degrade, never die
            hist = {}
        base["trend"] = shape_serve_trends(hist)
    return base


def serve_requests(limit: int = 100, slow: bool = False,
                   errors: bool = False,
                   timeout_s: float = 10.0) -> List[dict]:
    """Recent structured access-log rows gathered from every serve
    replica's ring (``rtpu requests``): request_id, deployment,
    replica, route, status, latency, queue wait, batch size. ``slow``
    keeps rows at/over ``serve_slow_request_threshold_s``, ``errors``
    keeps failures. Empty when serve is not running."""
    from .. import get, get_actor
    from ..serve.api import _CONTROLLER_NAME
    try:
        controller = get_actor(_CONTROLLER_NAME)
    except ValueError:
        return []
    import time as _time
    rows: List[dict] = []
    deadline = _time.monotonic() + timeout_s
    try:
        deployments = get(controller.list_deployments.remote(),
                          timeout=timeout_s)
        # submit the whole fan-out FIRST (replicas answer in parallel),
        # then collect under one shared deadline — a dead replica costs
        # at most the remaining budget once, not timeout_s serially per
        # replica; get_replicas discovery is fanned out the same way
        replica_refs = [controller.get_replicas.remote(name)
                        for name in deployments]
        refs = []
        for rref in replica_refs:
            for replica in get(rref, timeout=max(
                    0.5, deadline - _time.monotonic())):
                refs.append(replica.access_log.remote(limit, slow,
                                                      errors))
        for ref in refs:
            try:
                rows.extend(get(ref, timeout=max(
                    0.5, deadline - _time.monotonic())))
            except Exception:   # noqa: BLE001 — a dead replica is a
                continue        # gap, not a failure
    except Exception:   # noqa: BLE001 — controller mid-shutdown
        return rows
    rows.sort(key=lambda r: r.get("ts") or 0)
    return rows[-limit:]


def summarize_tasks() -> Dict[str, Any]:
    """Count by (name, state) — reference: ``ray summary tasks``."""
    return summarize_task_rows(list_tasks(limit=10**9))


def summarize_actors() -> Dict[str, Any]:
    return summarize_actor_rows(list_actors(limit=10**9))


# ------------------------------------------------- debugging & profiling

def cluster_stacks(timeout_s: float = 5.0) -> dict:
    """Thread dumps from every live node/worker/driver process,
    deduplicated by the control plane (reference: ``ray stack``).
    Returns ``{"nodes": {node_hex: [dump, ...]}, "groups": [...]}``
    where each group collapses threads with identical stacks."""
    return _ctx.require_client().cluster_stacks(timeout_s) or {}


def profile(duration_s: float = 5.0, interval_ms: Optional[float] = None,
            task_filter: Optional[str] = None,
            collapsed_file: Optional[str] = None,
            chrome_trace_file: Optional[str] = None) -> dict:
    """Cluster-wide sampling wall-clock profiler: every worker samples
    its threads for ``duration_s`` (capped by ``profiler_max_duration_s``)
    and the merged collapsed stacks come back flamegraph-ready.
    ``task_filter`` restricts samples to moments a task whose name
    contains the substring is running. Optionally writes a
    ``stack count``-per-line collapsed file and/or a Chrome trace."""
    from .._private import debugging
    from .._private.config import CONFIG

    opts: Dict[str, Any] = {
        "duration_s": duration_s,
        "interval_ms": interval_ms or CONFIG.profiler_default_interval_ms,
    }
    if task_filter:
        opts["task_filter"] = task_filter
    report = _ctx.require_client().cluster_profile(opts) or {}
    if collapsed_file:
        debugging.write_collapsed(report.get("collapsed") or {},
                                  collapsed_file)
    if chrome_trace_file:
        reports = [r for reps in (report.get("nodes") or {}).values()
                   for r in reps]
        with open(chrome_trace_file, "w") as f:
            json.dump(debugging.chrome_trace(reports), f)
    return report


def collective_health(timeout_s: float = 2.0) -> dict:
    """Cluster-wide collective hang & straggler diagnosis (the flight-
    recorder surface): every rank's per-op watermarks plus verdicts for
    stuck ops — ``dead_rank`` (process answered nothing), ``lost_chunk``
    (sender logged the send, receiver never saw the delivery — the edge
    is named) or ``lagging_rank`` (lowest watermark, with its current
    stack attached when a dump matches). Returns
    ``{"ops": [...], "verdicts": [...], "processes": n}``."""
    return _ctx.require_client().collective_health(timeout_s) or {}


def flight_records(timeout_s: float = 2.0) -> dict:
    """Raw per-process collective flight-recorder snapshots: the recent
    event ring (send/deliver/recv per chunk with monotonic timestamps)
    and completed-op records, keyed by node —
    ``{"nodes": {node_hex: [snapshot, ...]}}``."""
    return _ctx.require_client().flight_records(timeout_s) or {}


def metrics_history(name: Optional[str] = None,
                    tags: Optional[dict] = None,
                    window: Optional[float] = None,
                    step: Optional[float] = None,
                    shape: str = "value") -> Dict[str, Any]:
    """Windowed time series from the control plane's multi-resolution
    retention ring: aligned ``[ts, value]`` points per (name, tags)
    series over the trailing ``window`` seconds, at the finest retained
    resolution covering it (or the level nearest an explicit ``step``).
    ``shape`` turns cumulative counter/histogram series into usable
    curves: ``rate`` (per-second) or ``delta`` (per-step); gauges and
    digest series (whose points already carry interval p50/p95/p99)
    ignore it. Empty when ``metrics_history_capacity=0``."""
    if shape not in ("value", "rate", "delta"):
        raise ValueError(f"unknown shape {shape!r} (value | rate | delta)")
    res = _query("metrics_history", {"name": name, "tags": tags,
                                     "window": window, "step": step}) or {}
    if shape != "value":
        from .._private import history as _h
        for s in res.get("series") or []:
            if s.get("kind") in ("counter", "histogram"):
                s["points"] = _h.shape_points(s["points"], shape)
                s["shape"] = shape
    return res


def metrics_trends(window: float = 120.0) -> List[dict]:
    """Named movements over the trailing window (the doctor's trend
    section): rising watchlist gauges, serve p95 inflation, error-rate
    growth, idle-node-while-queueing. Empty on a quiet cluster."""
    from .._private import history as _h
    res = _query("metrics_history", {"window": float(window)}) or {}
    return _h.compute_trends(res)


def list_lifecycle_events(limit: int = 10000,
                          since: Optional[float] = None) -> List[dict]:
    """Node/actor/placement-group state transitions retained past
    death (bounded ring beside the task-event ring): what the cluster
    was doing, even for subjects that no longer exist."""
    rows = _query("lifecycle") or []
    if since is not None:
        rows = [r for r in rows if (r.get("ts") or 0) >= since]
    return rows[-limit:]


def events_stats() -> Dict[str, Any]:
    """Cluster-event ring occupancy + the eviction counter behind
    ``rtpu_events_evicted_total`` (silent history loss, observable)."""
    return _query("events_stats") or {}


_DOCTOR_TREND_WINDOW_S = 120.0


def gather_health_data(trend_window: float = _DOCTOR_TREND_WINDOW_S
                       ) -> Dict[str, Any]:
    """Collect every input ``build_health_report`` consumes from the
    live cluster, as JSON-able rows. Debug bundles store this same
    shape section-by-section, so ``rtpu autopsy`` rebuilds the doctor
    offline from a captured dict instead of live queries."""
    client = _ctx.require_client()
    data: Dict[str, Any] = {
        "nodes": shape_nodes(client.cluster_info("nodes") or []),
        "resources": {
            "total": client.cluster_info("resources_total") or {},
            "available": client.cluster_info("resources_available") or {},
        },
        "tasks": shape_tasks(_query("tasks")),
        "actors": shape_actors(_query("actors")),
        "events": _query("cluster_events") or [],
    }
    try:
        data["collectives"] = collective_health(1.5) or {}
    except Exception:   # noqa: BLE001 — doctor degrades, never dies
        data["collectives"] = {}
    try:
        mem = _query("memory") or {}
    except Exception:   # noqa: BLE001 — doctor degrades, never dies
        mem = {}
    data["memory"] = {"objects": shape_objects(mem.get("objects")),
                      "leaks": shape_leaks(mem.get("leaks"))}
    # ONE cluster-wide metrics snapshot, shared by the serve section
    # and the telemetry highlights (two identical head RPCs otherwise)
    try:
        data["metrics"] = shape_metrics(_query("metrics"))
    except Exception:   # noqa: BLE001 — doctor degrades, never dies
        data["metrics"] = []
    try:
        data["history"] = _query("metrics_history",
                                 {"window": float(trend_window)}) or {}
    except Exception:   # noqa: BLE001 — doctor degrades, never dies
        data["history"] = {}
    return data


def health_report() -> Dict[str, Any]:
    """`rtpu doctor`: one correlated cluster health view — node/resource
    state, task/actor rollups, stall diagnoses, recent WARNING/ERROR
    events, head-vs-tail trend movements over the retention window, and
    telemetry highlights (queue wait, store fill, dropped series)."""
    return build_health_report(gather_health_data())


def build_health_report(data: Dict[str, Any]) -> Dict[str, Any]:
    """Pure doctor: consumes the ``gather_health_data`` dict — live or
    replayed from a debug bundle (``rtpu autopsy``) with no cluster."""
    from .._private import history as _history
    nodes = data.get("nodes") or []
    total = (data.get("resources") or {}).get("total") or {}
    avail = (data.get("resources") or {}).get("available") or {}
    tasks = data.get("tasks") or []
    task_summary = summarize_task_rows(tasks)
    actor_rows = data.get("actors") or []
    actor_summary = summarize_actor_rows(actor_rows)
    events = data.get("events") or []
    recent = events[-500:]
    # a stall is a problem only while its task is still non-terminal:
    # historical TASK_STALL events for tasks that since finished/failed
    # must not keep the doctor red for the rest of the session
    current_state = {t["task_id"]: t["state"] for t in tasks}
    stalls = [e for e in recent
              if e.get("label") == "TASK_STALL"
              and current_state.get(e.get("task_id"))
              in ("PENDING_ARGS_AVAIL", "PENDING_NODE_ASSIGNMENT",
                  "RUNNING")]
    alerts = [e for e in recent
              if e.get("severity") in ("WARNING", "ERROR")
              and e.get("label") != "TASK_STALL"]
    coll = data.get("collectives") or {}
    coll_verdicts = coll.get("verdicts") or []
    mem = data.get("memory") or {}
    mem_rows = mem.get("objects") or []
    leaks = mem.get("leaks") or []

    highlights: Dict[str, Any] = {}
    metric_rows = data.get("metrics") or []
    try:
        serve = serve_health_from_rows(metric_rows)
    except Exception:   # noqa: BLE001 — doctor degrades, never dies
        serve = {"deployments": {}, "worst": None}
    try:
        metrics = summarize_metric_rows(metric_rows)
    except Exception:   # noqa: BLE001 — doctor degrades, never dies
        metrics = {}
    # trend section: head-vs-tail movements over the retention window
    # ("what changed", not just "what is") — empty when the history
    # plane is off or the window has no data
    try:
        trends = _history.compute_trends(data.get("history") or {})
    except Exception:   # noqa: BLE001 — doctor degrades, never dies
        trends = []
    queue_wait = metrics.get("rtpu_scheduler_queue_wait_seconds") or {}
    if queue_wait.get("count"):
        highlights["queue_wait_mean_s"] = round(
            queue_wait["sum"] / queue_wait["count"], 4)
    fill = metrics.get("rtpu_object_store_fill_ratio") or {}
    if "last" in fill:
        highlights["store_fill_ratio"] = fill["last"]
    dropped = metrics.get("rtpu_telemetry_dropped_series_total") or {}
    if dropped.get("total"):
        highlights["dropped_metric_series"] = dropped["total"]

    # recovery: did the self-healing machinery run, and did any budget
    # run dry? (reforms + actor checkpoint/restore counters from the
    # merged telemetry table; recent COLLECTIVE_REFORM/ACTOR_REROUTE
    # events; actors that died with restarts consumed = a budget that
    # was exhausted rather than never used)
    def _ctr(name: str) -> float:
        return (metrics.get(name) or {}).get("total", 0) or 0

    recovery = {
        "collective_reforms": _ctr("rtpu_collective_reforms_total"),
        "fenced_stale_chunks": _ctr("rtpu_collective_fenced_chunks_total"),
        "actor_checkpoints": _ctr("rtpu_actor_checkpoints_total"),
        "actor_restores": _ctr("rtpu_actor_restores_total"),
        "recent_reforms": [e for e in recent
                           if e.get("label") == "COLLECTIVE_REFORM"][-10:],
        "recent_actor_reroutes": [e for e in recent
                                  if e.get("label") == "ACTOR_REROUTE"][-10:],
        # exhausted = died having CONSUMED its whole (non-empty, finite)
        # budget: a deliberately-killed actor mid-budget, or one that
        # never had restarts, is not a crash loop worth flagging
        "exhausted_restart_budgets": [
            {"actor_id": a.get("actor_id"),
             "class_name": a.get("class_name"),
             "num_restarts": a.get("num_restarts", 0)}
            for a in actor_rows
            if a.get("state") == "DEAD"
            and 0 < a.get("max_restarts", 0) <= a.get("num_restarts", 0)],
    }

    dead_nodes = [n for n in nodes if not n.get("alive")]
    by_state = task_summary.get("by_state", {})
    n_pending = (by_state.get("PENDING_ARGS_AVAIL", 0)
                 + by_state.get("PENDING_NODE_ASSIGNMENT", 0))
    problems: List[str] = []
    if dead_nodes:
        named = ", ".join(str(n.get("node_id"))[:12]
                          for n in dead_nodes[:4])
        problems.append(f"{len(dead_nodes)} node(s) dead ({named})")
    if stalls:
        stalled = {e.get("task_id") for e in stalls}
        problems.append(f"{len(stalled)} stalled task(s) — see stalls")
    errors = [e for e in alerts if e.get("severity") == "ERROR"]
    if errors:
        problems.append(f"{len(errors)} ERROR event(s) — see alerts")
    cpu_avail = avail.get("CPU", 0.0)
    if n_pending and cpu_avail <= 0:
        problems.append(f"{n_pending} task(s) pending with 0 CPU "
                        "available (saturated or wedged)")
    if coll_verdicts:
        problems.append(f"{len(coll_verdicts)} stuck collective op(s) "
                        "— see collectives")
    if leaks:
        named = next((lk for lk in leaks if lk.get("callsite")), None)
        where = (f" — e.g. object created at {named['callsite']}"
                 if named else "")
        problems.append(f"{len(leaks)} leaked object(s){where} "
                        "— see memory")
    # serve: name the worst deployment (highest error rate, then p99);
    # a deployment failing a quarter of a real request volume is a
    # problem line, not just a table row
    worst_name = serve.get("worst")
    if worst_name:
        wd = serve["deployments"].get(worst_name) or {}
        if wd.get("error_rate", 0.0) >= 0.25 \
                and wd.get("requests_total", 0.0) >= 4:
            problems.append(
                f"deployment {worst_name!r} failing "
                f"{wd['error_rate']:.0%} of {wd['requests_total']:g} "
                "request(s) — see serve")
    # movements are problems too: a queue-wait p95 3x-ing over the
    # window is actionable before any instantaneous threshold trips
    for t in [t for t in trends if t.get("severity") == "warn"][:5]:
        problems.append(f"trend: {t['message']}")
    return {
        "healthy": not problems,
        "problems": problems,
        "trends": trends,
        "nodes": {"alive": len(nodes) - len(dead_nodes),
                  "dead": len(dead_nodes)},
        "resources": {"total": total, "available": avail},
        "tasks": task_summary,
        "actors": actor_summary,
        "stalls": stalls[-20:],
        "alerts": alerts[-20:],
        "collectives": {"ops": coll.get("ops") or [],
                        "verdicts": coll_verdicts},
        "memory": {"objects": len(mem_rows),
                   "bytes": sum(r.get("size") or 0 for r in mem_rows),
                   "leaked": len(leaks),
                   "leaks": leaks[:10]},
        "serve": serve,
        "recovery": recovery,
        "metrics": highlights,
    }


def list_events(filters: Optional[dict] = None,
                limit: int = 1000,
                since: Optional[float] = None,
                until: Optional[float] = None) -> List[dict]:
    """Structured cluster events — node up/down, OOM kills, actor
    deaths, stalls, leaks (reference: ``ray list cluster-events``).
    ``since``/``until`` are epoch-second bounds applied BEFORE the
    limit, so a time window never loses older matching rows to the
    cap; the ring's eviction counter (``rtpu_events_evicted_total`` /
    ``state.events_stats()``) says whether rows aged out of retention
    entirely."""
    rows = _query("cluster_events") or []
    if since is not None:
        rows = [r for r in rows if (r.get("timestamp") or 0) >= since]
    if until is not None:
        rows = [r for r in rows if (r.get("timestamp") or 0) <= until]
    return _apply_filters(rows, filters)[-limit:]


def list_cluster_events(filters: Optional[dict] = None,
                        limit: int = 1000,
                        since: Optional[float] = None,
                        until: Optional[float] = None) -> List[dict]:
    """Alias of ``list_events`` (the reference-flavored name)."""
    return list_events(filters, limit, since=since, until=until)


def list_spans(filters: Optional[dict] = None,
               limit: int = 10000) -> List[dict]:
    """Finished trace spans (requires ``tracing_enabled``)."""
    # ship this process's own buffered spans first, so driver-side
    # spans are visible mid-session (not only after shutdown)
    from ..util import tracing
    tracing.flush()
    rows = _query("spans") or []
    return _apply_filters(rows, filters)[-limit:]


def trace_timeline(filename: Optional[str] = None) -> Any:
    """Chrome-trace JSON built from SPANS (cross-process causality via
    trace/parent ids; requires ``tracing_enabled``). Complement of
    ``timeline()``, which is built from task state events."""
    trace = []
    for span in list_spans():
        if span.get("end_time") is None:
            continue
        trace.append({
            "name": span["name"],
            "cat": "span",
            "ph": "X",
            "ts": span["start_time"] * 1e6,
            "dur": (span["end_time"] - span["start_time"]) * 1e6,
            "pid": f"trace:{span['trace_id'][:8]}",
            "tid": f"pid:{span.get('pid', '?')}",
            "args": {"span_id": span["span_id"],
                     "parent_id": span.get("parent_id"),
                     "status": span.get("status"),
                     **span.get("attributes", {})},
        })
    if filename is not None:
        with open(filename, "w") as f:
            json.dump(trace, f)
        return filename
    return trace


def _collective_trace_events() -> List[dict]:
    """Completed collective ops from every process's flight recorder as
    Chrome-trace X events: one span per (rank, call), grouped per
    collective group so straggling ranks line up visually against their
    peers. Best-effort — a session with the recorder off (or no runtime)
    contributes nothing. Collective-free sessions skip the cluster
    fan-out entirely: a plain-task timeline must not pay a COLL_PROGRESS
    round trip to every process for an empty result."""
    from .._private import flight_recorder as _fr
    try:
        local_active = bool(_fr._groups or _fr._done or _fr._inflight)
        if not local_active:
            # ranks may live only in workers: the merged metrics table
            # (one STATE_QUERY) says whether ANY process ran collectives
            # (workers flush telemetry at task boundaries)
            counters = (_query("metrics") or {}).get("counters") or {}
            if not any(name == "rtpu_collective_ops_total"
                       for name, _tags in counters):
                return []
        records = flight_records(timeout_s=1.5)
    except Exception:   # noqa: BLE001 — timeline degrades, never dies
        return []
    trace: List[dict] = []
    for snaps in (records.get("nodes") or {}).values():
        for snap in snaps or []:
            for rec in snap.get("done", ()):
                start = rec.get("start")
                dur = rec.get("dur")
                if start is None or dur is None:
                    continue
                trace.append({
                    "name": f"coll::{rec.get('op')}",
                    "cat": "collective",
                    "ph": "X",
                    "ts": start * 1e6,
                    "dur": max(dur, 1e-6) * 1e6,
                    "pid": f"coll:{rec.get('group')}",
                    "tid": f"rank:{rec.get('rank')}",
                    "args": {"op": rec.get("op"),
                             "algo": rec.get("algo"),
                             "seq": rec.get("key"),
                             "nbytes": rec.get("nbytes"),
                             "world": rec.get("world"),
                             "chunks_sent": rec.get("sent"),
                             "chunks_recv": rec.get("recv"),
                             "error": rec.get("error")},
                })
    return trace


def _request_trace_events() -> List[dict]:
    """Serve request traces as Chrome-trace X events (``cat:
    "request"``): every span belonging to a trace that contains a
    ``request::`` span — the force-traced ingress/queue-wait/batch-
    assembly/replica-execute spans AND any nested ``task::``/
    ``actor_call::`` spans the deployment's own ``.remote()`` calls
    produced (they share the request's trace id) — grouped one pid row
    per request id, so one request reads as one timeline lane."""
    from ..util import tracing
    try:
        tracing.flush()
        spans = _query("spans") or []
    except Exception:   # noqa: BLE001 — timeline degrades, never dies
        return []
    by_trace: Dict[str, List[dict]] = defaultdict(list)
    for span in spans:
        tid = span.get("trace_id")
        if tid:
            by_trace[tid].append(span)
    trace: List[dict] = []
    for tid, members in by_trace.items():
        rid = None
        is_request = False
        for span in members:
            if str(span.get("name", "")).startswith("request::"):
                is_request = True
                rid = rid or (span.get("attributes")
                              or {}).get("request_id")
        if not is_request:
            continue                    # not a request trace
        pid = f"request:{rid or tid[:8]}"
        for span in members:
            if span.get("end_time") is None:
                continue
            trace.append({
                "name": span["name"],
                "cat": "request",
                "ph": "X",
                "ts": span["start_time"] * 1e6,
                "dur": max(span["end_time"] - span["start_time"],
                           1e-6) * 1e6,
                "pid": pid,
                "tid": f"pid:{span.get('pid', '?')}",
                "args": {"trace_id": tid,
                         "span_id": span.get("span_id"),
                         "parent_id": span.get("parent_id"),
                         "status": span.get("status"),
                         **(span.get("attributes") or {})},
            })
    return trace


def lifecycle_trace_events(rows: List[dict]) -> List[dict]:
    """Retained node/actor/PG state transitions as Chrome instant
    events (``ph: "i"``, one lane per subject kind) — pure, shared by
    ``timeline(lifecycle=True)`` and the offline autopsy replay."""
    trace = []
    for r in rows or []:
        trace.append({
            "name": f"{r.get('kind')}:{r.get('state')}",
            "cat": "lifecycle",
            "ph": "i",
            "s": "g",       # global-scope instant marker
            "ts": (r.get("ts") or 0) * 1e6,
            "pid": f"lifecycle:{r.get('kind')}",
            "tid": str(r.get("id"))[:12],
            "args": {k: v for k, v in r.items()
                     if k not in ("ts", "kind")},
        })
    return trace


def timeline(filename: Optional[str] = None,
             lifecycle: bool = False) -> Any:
    """Chrome-trace JSON of task execution (reference: ``ray.timeline``,
    ``_private/state.py:865``), plus one span per completed collective
    call from the flight recorder (``cat: collective``, one row per
    rank), plus one lane per traced serve request (``cat: request`` —
    ingress/queue-wait/batch-assembly/replica-execute and the
    request's nested task spans, keyed by request id).
    ``lifecycle=True`` adds instant markers for retained node/actor/PG
    state transitions (``cat: lifecycle``) so the trailing window shows
    what the cluster was doing around each death. Load the output in
    chrome://tracing or Perfetto."""
    events = _query("tasks") or []
    # pair RUNNING -> FINISHED/FAILED per task
    runs: Dict[Any, dict] = {}
    trace = []
    for ev in sorted(events, key=lambda e: e["timestamp"]):
        tid = ev["task_id"]
        node = (ev["node_id"].hex()[:8]
                if hasattr(ev["node_id"], "hex") else str(ev["node_id"]))
        if ev["state"] == "RUNNING":
            runs[tid] = ev
        elif ev["state"] in ("FINISHED", "FAILED") and tid in runs:
            start = runs.pop(tid)
            trace.append({
                "name": ev["name"],
                "cat": "actor_task" if ev.get("is_actor_task") else "task",
                "ph": "X",
                "ts": start["timestamp"] * 1e6,
                "dur": (ev["timestamp"] - start["timestamp"]) * 1e6,
                "pid": f"node:{node}",
                "tid": (tid.hex()[:8] if hasattr(tid, "hex")
                        else str(tid)),
                "args": {"state": ev["state"]},
            })
    trace.extend(_collective_trace_events())
    trace.extend(_request_trace_events())
    if lifecycle:
        try:
            trace.extend(lifecycle_trace_events(_query("lifecycle")))
        except Exception:   # noqa: BLE001 — timeline degrades, never dies
            pass
    if filename is not None:
        with open(filename, "w") as f:
            json.dump(trace, f)
        return filename
    return trace
