"""serve public API: @deployment, run, handles, HTTP gateway.

Reference: ``serve/api.py:479`` (serve.run), ``:265`` (@serve.deployment),
proxies ``_private/proxy.py``. The gateway here is stdlib http.server
(JSON POST /{deployment}) — the reference's uvicorn/ASGI stack is an
infra choice, not a semantic one; routing semantics (handle + p2c) are
identical for HTTP and Python callers.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Any, Callable, Dict, Optional

from .. import get, get_actor, kill
from .._private import serialization as ser
from .controller import ServeController
from .handle import DeploymentHandle

_CONTROLLER_NAME = "rtpu:serve_controller"
_http_server = None


class Deployment:
    """Declarative deployment spec; ``.bind(*args)`` makes an app."""

    def __init__(self, target: Callable, name: str,
                 num_replicas: int = 1,
                 ray_actor_options: Optional[dict] = None,
                 autoscaling_config: Optional[dict] = None,
                 max_concurrent_queries: int = 8):
        self._target = target
        self.name = name
        self.num_replicas = num_replicas
        self.ray_actor_options = ray_actor_options or {}
        self.autoscaling_config = autoscaling_config
        self.max_concurrent_queries = max_concurrent_queries

    def options(self, **kwargs) -> "Deployment":
        merged = dict(
            name=self.name, num_replicas=self.num_replicas,
            ray_actor_options=self.ray_actor_options,
            autoscaling_config=self.autoscaling_config,
            max_concurrent_queries=self.max_concurrent_queries)
        merged.update(kwargs)
        return Deployment(self._target, **merged)

    def bind(self, *init_args, **init_kwargs) -> "Application":
        return Application(self, init_args, init_kwargs)


class Application:
    def __init__(self, deployment: Deployment, init_args: tuple,
                 init_kwargs: dict):
        self.deployment = deployment
        self.init_args = init_args
        self.init_kwargs = init_kwargs


def deployment(_target: Optional[Callable] = None, *,
               name: Optional[str] = None, num_replicas: int = 1,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[dict] = None,
               max_concurrent_queries: int = 8):
    """``@serve.deployment`` on a class (callable) or function."""

    def wrap(target):
        return Deployment(target, name or target.__name__,
                          num_replicas=num_replicas,
                          ray_actor_options=ray_actor_options,
                          autoscaling_config=autoscaling_config,
                          max_concurrent_queries=max_concurrent_queries)

    if _target is not None:
        return wrap(_target)
    return wrap


def _get_or_create_controller():
    try:
        return get_actor(_CONTROLLER_NAME)
    except ValueError:
        return ServeController.options(name=_CONTROLLER_NAME,
                                       lifetime="detached").remote()


def run(app: Application, *, name: Optional[str] = None,
        route_prefix: Optional[str] = None) -> DeploymentHandle:
    """Deploy an application; blocks until replicas exist."""
    dep = app.deployment
    controller = _get_or_create_controller()
    blob = ser.dumps_function(dep._target)
    get(controller.deploy.remote(
        dep.name, blob, app.init_args, app.init_kwargs,
        dep.num_replicas, dep.ray_actor_options,
        dep.autoscaling_config, dep.max_concurrent_queries))
    return DeploymentHandle(dep.name, controller)


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name, _get_or_create_controller())


def delete(name: str) -> None:
    controller = _get_or_create_controller()
    get(controller.delete.remote(name))


def shutdown() -> None:
    stop_http()
    stop_grpc()
    try:
        from .proxy import stop_proxies
        stop_proxies()
    except Exception:   # noqa: BLE001 — proxies are best-effort on exit
        pass
    try:
        controller = get_actor(_CONTROLLER_NAME)
    except ValueError:
        return
    get(controller.shutdown.remote())
    try:
        kill(controller)
    except Exception:
        pass


# ------------------------------------------------------------- HTTP gateway

class _GatewayHandler:
    """Shared dispatch for the JSON gateway (reference: HTTPProxy,
    ``_private/proxy.py:912``): ``POST /{deployment}`` calls the
    deployment with the parsed JSON body, ``GET /{deployment}`` calls it
    with the query params (or None), ``GET /-/routes`` lists routes.
    Unknown deployments are 404, deployment exceptions 500."""

    _ROUTES_TTL_S = 2.0

    def __init__(self):
        self._handles: Dict[str, DeploymentHandle] = {}
        self._routes_cache: Dict[str, str] = {}
        self._routes_at = 0.0

    def routes(self) -> Dict[str, str]:
        # TTL-cached: the 404 check must not put a controller RPC on
        # every data-path request
        now = time.monotonic()
        if now - self._routes_at > self._ROUTES_TTL_S:
            ctrl = _get_or_create_controller()
            self._routes_cache = {
                f"/{name}": name
                for name in get(ctrl.list_deployments.remote())}
            self._routes_at = now
        return self._routes_cache

    def _handle(self, name: str):
        handle = self._handles.get(name)
        if handle is None:
            handle = get_deployment_handle(name)
            self._handles[name] = handle
        return handle

    @contextlib.contextmanager
    def _ingress(self, name: str, request_id: Optional[str],
                 proto: str, stream: bool):
        """One request's ingress scope: mint/adopt the request id, bind
        the request context the handle ships to the replica, and open
        the force-traced ``request::ingress`` span — so the whole
        request is one trace even when ``tracing_enabled`` is off. The
        span covers whatever runs inside the scope (unary: routing +
        result wait; streaming: routing/submission only — stream
        latency is recorded replica-side at exhaustion). Span shipping
        is rate-limited; the timeline/list_spans readers flush the
        tail themselves."""
        from . import request_context as _rc
        from ..util import tracing
        meta = _rc.make(name, request_id=request_id, proto=proto)
        if stream:
            meta["stream"] = True
        attributes = {"request_id": meta["request_id"],
                      "deployment": name, "route": meta["route"],
                      "proto": proto}
        if stream:
            attributes["stream"] = True
        token = _rc.bind(meta)
        try:
            with tracing.start_span("request::" + "ingress",
                                    attributes=attributes, force=True):
                yield
        finally:
            _rc.unbind(token)
            tracing.maybe_flush()

    def call(self, name: str, arg, model_id: Optional[str] = None,
             request_id: Optional[str] = None, proto: str = "http"):
        """One unary request through the gateway (caller-supplied
        ``X-Request-ID`` honored via ``request_id``)."""
        from . import request_context as _rc
        handle = self._handle(name)
        if model_id:
            handle = handle.options(multiplexed_model_id=model_id)
        if not _rc.enabled():
            return handle.remote(arg).result(timeout=30.0)
        with self._ingress(name, request_id, proto, stream=False):
            return handle.remote(arg).result(timeout=30.0)

    def stream(self, name: str, arg, model_id: Optional[str] = None,
               request_id: Optional[str] = None, proto: str = "http"):
        """Iterator of item values from a streaming deployment handler
        (generator)."""
        from . import request_context as _rc
        handle = self._handle(name)
        if model_id:
            handle = handle.options(multiplexed_model_id=model_id)
        if not _rc.enabled():
            return handle.stream(arg)
        with self._ingress(name, request_id, proto, stream=True):
            return handle.stream(arg)


def _gateway_server(host: str = "127.0.0.1", port: int = 0):
    """Build + start one gateway HTTP server; returns (server, address).
    Used by the driver-local ``start_http`` and by each per-node
    ``ProxyActor`` (reference: one HTTPProxy per node,
    ``_private/proxy.py:613``)."""
    from .._private.http_util import HttpServerBase, JsonHandler

    gateway = _GatewayHandler()

    class Handler(JsonHandler):
        def _dispatch(self, arg_from_body: bool):
            from . import request_context as _rc
            path, _, query = self.path.partition("?")
            name = path.strip("/").split("/")[0]
            # inbound X-Request-ID is honored (distributed callers
            # stitch their own ids through); otherwise minted here —
            # either way the response echoes it in X-RTPU-Request-ID
            rid = None
            rid_headers = {}
            if _rc.enabled():
                rid = (self.headers.get("X-Request-ID")
                       or _rc.new_request_id())
                rid_headers = {"X-RTPU-Request-ID": rid}
            try:
                if path.rstrip("/") == "/-/routes":
                    return self._json(200, gateway.routes())
                if not name or f"/{name}" not in gateway.routes():
                    return self._json(404,
                                      {"error": f"no deployment {name!r}"},
                                      headers=rid_headers)
                if arg_from_body:
                    # an EMPTY body means "no argument" (None), matching
                    # the GET-without-query semantics below
                    n = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(n)
                    arg = json.loads(raw) if raw else None
                else:
                    from urllib.parse import parse_qs
                    q = {k: v[0] if len(v) == 1 else v
                         for k, v in parse_qs(query).items()}
                    arg = q or None
                if self.headers.get("X-RTPU-Stream"):
                    # streaming response: one JSON line per produced
                    # item, written (and flushed) as each arrives —
                    # the client reads incrementally until EOF
                    # (reference: Serve StreamingResponse,
                    # ``_private/proxy.py`` ASGI streaming).
                    # Pull the FIRST item before committing the 200 so
                    # an immediately-failing handler gets a real 500;
                    # later errors become a terminal {"error": ...}
                    # line (headers are already on the wire by then).
                    stream_it = iter(gateway.stream(name, arg,
                                                    request_id=rid))
                    first = _STREAM_END = object()
                    try:
                        first = next(stream_it)
                    except StopIteration:
                        pass
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Connection", "close")
                    for key, value in rid_headers.items():
                        self.send_header(key, value)
                    self.end_headers()

                    def write_line(obj) -> None:
                        self.wfile.write(
                            (json.dumps(obj) + "\n").encode())
                        self.wfile.flush()

                    try:
                        if first is not _STREAM_END:
                            write_line({"item": first})
                            for item in stream_it:
                                write_line({"item": item})
                    except Exception as e:  # noqa: BLE001 — terminal line
                        write_line({"error": str(e)})
                    return None
                result = gateway.call(name, arg, request_id=rid)
                return self._json(200, {"result": result},
                                  headers=rid_headers)
            except Exception as e:   # noqa: BLE001 — always answer JSON
                return self._json(500, {"error": str(e)},
                                  headers=rid_headers)

        def do_POST(self):
            self._dispatch(arg_from_body=True)

        def do_GET(self):
            self._dispatch(arg_from_body=False)

    class Gateway(HttpServerBase):
        thread_name = "rtpu-serve-http"

    server = Gateway(Handler, host=host, port=port)
    server.start()
    return server, f"http://{host}:{server.port}"


def start_http(host: str = "127.0.0.1", port: int = 8000) -> str:
    global _http_server
    # restarting replaces the gateway: the old thread/port must not be
    # orphaned (they'd hold the bind until process exit)
    stop_http()
    _http_server, addr = _gateway_server(host, port)
    return addr


def start(*, proxy_location: str = "HeadOnly",
          http_host: str = "127.0.0.1", http_port: int = 0):
    """Start Serve ingress (reference: ``serve.start(http_options=...)``
    + ``ProxyStateManager``). ``proxy_location``:

    * ``"HeadOnly"`` — one gateway in this driver process.
    * ``"EveryNode"`` — a detached ProxyActor per alive cluster node,
      each serving every deployment; returns {node_id_hex: address}.
    """
    _get_or_create_controller()
    if proxy_location == "EveryNode":
        from .proxy import ensure_proxies
        return ensure_proxies(http_host, http_port)
    if proxy_location == "HeadOnly":
        return start_http(http_host, http_port or 8000)
    raise ValueError(
        f"proxy_location must be 'HeadOnly' or 'EveryNode', "
        f"got {proxy_location!r}")


_grpc_server = None


def start_grpc(host: str = "127.0.0.1", port: int = 0) -> str:
    """Start the gRPC ingress (reference: serve's gRPCProxy); returns
    "host:port". See ``serve/grpc_ingress.py`` for the wire contract."""
    global _grpc_server
    stop_grpc()
    from .grpc_ingress import start_grpc as _start
    _grpc_server, addr = _start(host, port)
    return addr


def stop_grpc() -> None:
    global _grpc_server
    if _grpc_server is not None:
        _grpc_server.stop(grace=None)
        _grpc_server = None


def stop_http() -> None:
    global _http_server
    if _http_server is not None:
        _http_server.stop()
        _http_server = None
