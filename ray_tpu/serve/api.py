"""serve public API: @deployment, run, handles, HTTP gateway.

Reference: ``serve/api.py:479`` (serve.run), ``:265`` (@serve.deployment),
proxies ``_private/proxy.py``. The gateway here is stdlib http.server
(JSON POST /{deployment}) — the reference's uvicorn/ASGI stack is an
infra choice, not a semantic one; routing semantics (handle + p2c) are
identical for HTTP and Python callers.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, Dict, Optional

from .. import get, get_actor, kill
from .._private import serialization as ser
from .controller import ServeController
from .handle import DeploymentHandle

_CONTROLLER_NAME = "rtpu:serve_controller"
_http_server = None


class Deployment:
    """Declarative deployment spec; ``.bind(*args)`` makes an app."""

    def __init__(self, target: Callable, name: str,
                 num_replicas: int = 1,
                 ray_actor_options: Optional[dict] = None,
                 autoscaling_config: Optional[dict] = None,
                 max_concurrent_queries: int = 8):
        self._target = target
        self.name = name
        self.num_replicas = num_replicas
        self.ray_actor_options = ray_actor_options or {}
        self.autoscaling_config = autoscaling_config
        self.max_concurrent_queries = max_concurrent_queries

    def options(self, **kwargs) -> "Deployment":
        merged = dict(
            name=self.name, num_replicas=self.num_replicas,
            ray_actor_options=self.ray_actor_options,
            autoscaling_config=self.autoscaling_config,
            max_concurrent_queries=self.max_concurrent_queries)
        merged.update(kwargs)
        return Deployment(self._target, **merged)

    def bind(self, *init_args, **init_kwargs) -> "Application":
        return Application(self, init_args, init_kwargs)


class Application:
    def __init__(self, deployment: Deployment, init_args: tuple,
                 init_kwargs: dict):
        self.deployment = deployment
        self.init_args = init_args
        self.init_kwargs = init_kwargs


def deployment(_target: Optional[Callable] = None, *,
               name: Optional[str] = None, num_replicas: int = 1,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[dict] = None,
               max_concurrent_queries: int = 8):
    """``@serve.deployment`` on a class (callable) or function."""

    def wrap(target):
        return Deployment(target, name or target.__name__,
                          num_replicas=num_replicas,
                          ray_actor_options=ray_actor_options,
                          autoscaling_config=autoscaling_config,
                          max_concurrent_queries=max_concurrent_queries)

    if _target is not None:
        return wrap(_target)
    return wrap


def _get_or_create_controller():
    try:
        return get_actor(_CONTROLLER_NAME)
    except ValueError:
        return ServeController.options(name=_CONTROLLER_NAME,
                                       lifetime="detached").remote()


def run(app: Application, *, name: Optional[str] = None,
        route_prefix: Optional[str] = None) -> DeploymentHandle:
    """Deploy an application; blocks until replicas exist."""
    dep = app.deployment
    controller = _get_or_create_controller()
    blob = ser.dumps_function(dep._target)
    get(controller.deploy.remote(
        dep.name, blob, app.init_args, app.init_kwargs,
        dep.num_replicas, dep.ray_actor_options,
        dep.autoscaling_config, dep.max_concurrent_queries))
    return DeploymentHandle(dep.name, controller)


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name, _get_or_create_controller())


def delete(name: str) -> None:
    controller = _get_or_create_controller()
    get(controller.delete.remote(name))


def shutdown() -> None:
    global _http_server
    if _http_server is not None:
        _http_server.shutdown()
        _http_server = None
    try:
        controller = get_actor(_CONTROLLER_NAME)
    except ValueError:
        return
    get(controller.shutdown.remote())
    try:
        kill(controller)
    except Exception:
        pass


# ------------------------------------------------------------- HTTP gateway

def start_http(host: str = "127.0.0.1", port: int = 8000) -> str:
    """Minimal JSON gateway: POST /{deployment} with a JSON body calls
    the deployment with the parsed body (reference: HTTPProxy
    ``_private/proxy.py:912``)."""
    global _http_server
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    handles: Dict[str, DeploymentHandle] = {}

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            name = self.path.strip("/").split("/")[0]
            try:
                handle = handles.get(name)
                if handle is None:
                    handle = get_deployment_handle(name)
                    handles[name] = handle
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"null")
                result = handle.remote(body).result(timeout=30.0)
                payload = json.dumps({"result": result},
                                     default=str).encode()
                self.send_response(200)
            except Exception as e:
                payload = json.dumps({"error": str(e)}).encode()
                self.send_response(500)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):
            pass

    _http_server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=_http_server.serve_forever,
                     daemon=True).start()
    return f"http://{host}:{_http_server.server_address[1]}"
