"""ServeController — declarative state reconciliation + autoscaling.

Reference: ``serve/controller.py:80`` (ServeController actor),
``_private/deployment_state.py:2258`` (DeploymentStateManager.update —
diff target vs actual, start/stop replicas), ``_private/
autoscaling_policy.py`` (queue-depth driven replica counts). One
controller actor owns all deployment state; handles poll it for replica
lists (the reference pushes via long-poll — polling with a TTL is the
same contract with simpler liveness).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from .._private import locksan
from ..api import remote


@remote(num_cpus=0, max_concurrency=8)
class ServeController:
    def __init__(self):
        # name -> {"deployment": Deployment, "replicas": [handles],
        #          "target": int}
        self._deployments: Dict[str, dict] = {}
        self._lock = locksan.lock("serve.controller")
        # (due_ts, metric, tags) for a second gauge_delete ~1s after a
        # replica kill: kill() is async, so the dying replica can still
        # publish its queue depth with a ts NEWER than the immediate
        # delete marker (the plane's tombstone only refuses older-ts
        # stragglers); once the process is actually dead a re-delete
        # is strictly the newest write and retires the series for good
        self._retire_queue: List[tuple] = []
        self._last_replica_health = 0.0
        self._autoscale_thread = threading.Thread(
            target=self._autoscale_loop, daemon=True)
        self._autoscale_thread.start()

    # ------------------------------------------------------------ lifecycle
    def deploy(self, name: str, deployment_blob: bytes,
               init_args: tuple, init_kwargs: dict,
               num_replicas: int, ray_actor_options: dict,
               autoscaling_config: Optional[dict],
               max_concurrency: int) -> None:
        from .._private import serialization as ser
        cls = ser.loads_function(deployment_blob)
        with self._lock:
            rec = self._deployments.get(name)
            if rec is None:
                rec = {"replicas": [], "target": 0}
                self._deployments[name] = rec
            rec.update(
                cls_blob=deployment_blob, cls=cls,
                init_args=init_args, init_kwargs=init_kwargs,
                actor_options=ray_actor_options or {},
                autoscaling=autoscaling_config,
                max_concurrency=max_concurrency,
                target=num_replicas)
        self._reconcile(name)

    def delete(self, name: str) -> None:
        with self._lock:
            rec = self._deployments.pop(name, None)
        if rec:
            tags = rec.get("replica_tags") or []
            pairs = [(r, tags[i] if i < len(tags) else None)
                     for i, r in enumerate(rec["replicas"])]
            self._stop_replicas(pairs, name)

    def shutdown(self) -> None:
        with self._lock:
            names = list(self._deployments)
        for n in names:
            self.delete(n)

    # ---------------------------------------------------------- introspection
    def get_replicas(self, name: str) -> List[Any]:
        with self._lock:
            rec = self._deployments.get(name)
            return list(rec["replicas"]) if rec else []

    def list_deployments(self) -> Dict[str, int]:
        with self._lock:
            return {n: len(r["replicas"])
                    for n, r in self._deployments.items()}

    # ------------------------------------------------------------- internals
    def _reconcile(self, name: str) -> None:
        from . import replica as rep
        with self._lock:
            rec = self._deployments.get(name)
            if rec is None:
                return
            want = rec["target"]
            have = len(rec["replicas"])
            cls_blob = rec["cls_blob"]
            args, kwargs = rec["init_args"], rec["init_kwargs"]
            opts = dict(rec["actor_options"])
            opts.setdefault("max_concurrency", rec["max_concurrency"])
        while have < want:
            with self._lock:
                # monotonic per-deployment tag (indices shift as
                # replicas stop; the tag names THIS replica forever in
                # access logs, metrics and worker log prefixes)
                tag = rec["next_replica_seq"] = \
                    rec.get("next_replica_seq", 0) + 1
            replica = rep.Replica.options(**opts).remote(
                cls_blob, args, kwargs, name, str(tag - 1))
            with self._lock:
                rec["replicas"].append(replica)
                rec.setdefault("replica_tags", []).append(str(tag - 1))
            have += 1
        excess = []
        with self._lock:
            tags = rec.setdefault("replica_tags", [])
            while len(rec["replicas"]) > want:
                excess.append((rec["replicas"].pop(),
                               tags.pop() if tags else None))
        self._stop_replicas(excess, name)

    def _stop_replicas(self, replicas: List[Any], name: str) -> None:
        from .. import kill
        zeroed = False
        for r, tag in replicas:
            try:
                kill(r)
            except Exception:
                pass
            if tag is not None:
                zeroed = True
                # retire the stopped replica's queue-depth series so
                # serve_health's sum/table — and every raw gauge
                # surface (Prometheus scrape, dashboard, summary) —
                # forget the dead replica instead of reporting its
                # last value forever
                self._retire_replica_series(name, tag)
        if zeroed:
            # ship the zeros NOW: the controller itself may be killed
            # right after a delete (serve.shutdown), and the
            # rate-limited task-boundary flush could skip them
            from .._private import telemetry
            telemetry.flush()

    def _retire_replica_series(self, name: str, tag: str) -> None:
        """One replica's gauge series -> the delete/tombstone path (the
        immediate marker plus a ~1s re-delete, see _retire_queue)."""
        from . import replica as rep
        from .._private import telemetry
        tags = (("deployment", name or "default"), ("replica", tag))
        telemetry.gauge_delete(rep.M_SERVE_QUEUE_DEPTH, tags)
        with self._lock:
            self._retire_queue.append(
                (time.time() + 1.0, rep.M_SERVE_QUEUE_DEPTH, tags))

    def _check_replica_health(self) -> None:
        """Replica-DEATH observation (the PR-13 open gap): a replica
        that CRASHES — rather than being scaled down — leaves its
        queue-depth gauge series live forever, because only the
        controlled stop path retired it. Poll the control plane's
        actor table (~1/s, one STATE_QUERY) and route every dead
        replica through the same gauge_delete/tombstone path, dropping
        the dead handle so fan-outs stop paying its timeout. The
        target count is untouched; the next reconcile/autoscale pass
        decides whether to replace the capacity."""
        now = time.time()
        if now - self._last_replica_health < 1.0:
            return
        self._last_replica_health = now
        from .._private import context as _ctx
        client = _ctx.current_client
        if client is None:
            return
        try:
            rows = client.state_query("actors", None) or []
        except Exception:   # noqa: BLE001 — health poll is best-effort
            return
        dead = set()
        for r in rows:
            if r.get("state") == "DEAD":
                aid = r.get("actor_id")
                dead.add(aid.hex() if hasattr(aid, "hex") else str(aid))
        if not dead:
            return
        crashed: List[tuple] = []
        with self._lock:
            for name, rec in self._deployments.items():
                tags = rec.setdefault("replica_tags", [])
                keep_r: List[Any] = []
                keep_t: List[Any] = []
                for i, r in enumerate(rec["replicas"]):
                    tag = tags[i] if i < len(tags) else None
                    if r.actor_id.hex() in dead:
                        crashed.append((name, tag))
                    else:
                        keep_r.append(r)
                        keep_t.append(tag)
                rec["replicas"] = keep_r
                rec["replica_tags"] = keep_t
        zeroed = False
        for name, tag in crashed:
            if tag is not None:
                zeroed = True
                self._retire_replica_series(name, tag)
        if zeroed:
            from .._private import telemetry
            telemetry.flush()
        # restore target capacity: the target count is unchanged, so a
        # reconcile spawns replacements (fresh tags) — without this, a
        # non-autoscaling deployment shrinks forever and an autoscaling
        # one whose replicas ALL crashed is skipped by the autoscale
        # loop's empty-replica guard and never recovers
        for name in {n for n, _t in crashed}:
            try:
                self._reconcile(name)
            except Exception:   # noqa: BLE001 — next pass retries
                pass

    def _flush_retires(self) -> None:
        now = time.time()
        with self._lock:
            due = [e for e in self._retire_queue if e[0] <= now]
            self._retire_queue = [e for e in self._retire_queue
                                  if e[0] > now]
        if due:
            from .._private import telemetry
            for _ts, metric, tags in due:
                telemetry.gauge_delete(metric, tags)
            telemetry.flush()

    def _autoscale_loop(self) -> None:
        from .. import get
        while True:
            time.sleep(0.25)
            self._flush_retires()
            try:
                self._check_replica_health()
            except Exception:   # noqa: BLE001 — observation only
                pass
            with self._lock:
                items = [(n, rec) for n, rec in self._deployments.items()
                         if rec.get("autoscaling")]
            for name, rec in items:
                try:
                    cfg = rec["autoscaling"]
                    with self._lock:
                        replicas = list(rec["replicas"])
                    if not replicas:
                        continue
                    depths = get([r.queue_depth.remote()
                                  for r in replicas], timeout=2.0)
                    avg = sum(depths) / len(depths)
                    target_per = cfg.get(
                        "target_num_ongoing_requests_per_replica", 2)
                    want = len(replicas)
                    if avg > target_per:
                        want += 1
                    elif avg < target_per / 2 and want > 1:
                        want -= 1
                    want = max(cfg.get("min_replicas", 1),
                               min(cfg.get("max_replicas", 4), want))
                    if want != len(replicas):
                        with self._lock:
                            rec["target"] = want
                        self._reconcile(name)
                except Exception:
                    continue
