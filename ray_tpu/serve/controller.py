"""ServeController — declarative state reconciliation + autoscaling.

Reference: ``serve/controller.py:80`` (ServeController actor),
``_private/deployment_state.py:2258`` (DeploymentStateManager.update —
diff target vs actual, start/stop replicas), ``_private/
autoscaling_policy.py`` (queue-depth driven replica counts). One
controller actor owns all deployment state; handles poll it for replica
lists (the reference pushes via long-poll — polling with a TTL is the
same contract with simpler liveness).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from .._private import locksan
from ..api import remote


@remote(num_cpus=0, max_concurrency=8)
class ServeController:
    def __init__(self):
        # name -> {"deployment": Deployment, "replicas": [handles],
        #          "target": int}
        self._deployments: Dict[str, dict] = {}
        self._lock = locksan.lock("serve.controller")
        self._autoscale_thread = threading.Thread(
            target=self._autoscale_loop, daemon=True)
        self._autoscale_thread.start()

    # ------------------------------------------------------------ lifecycle
    def deploy(self, name: str, deployment_blob: bytes,
               init_args: tuple, init_kwargs: dict,
               num_replicas: int, ray_actor_options: dict,
               autoscaling_config: Optional[dict],
               max_concurrency: int) -> None:
        from .._private import serialization as ser
        cls = ser.loads_function(deployment_blob)
        with self._lock:
            rec = self._deployments.get(name)
            if rec is None:
                rec = {"replicas": [], "target": 0}
                self._deployments[name] = rec
            rec.update(
                cls_blob=deployment_blob, cls=cls,
                init_args=init_args, init_kwargs=init_kwargs,
                actor_options=ray_actor_options or {},
                autoscaling=autoscaling_config,
                max_concurrency=max_concurrency,
                target=num_replicas)
        self._reconcile(name)

    def delete(self, name: str) -> None:
        with self._lock:
            rec = self._deployments.pop(name, None)
        if rec:
            self._stop_replicas(rec["replicas"])

    def shutdown(self) -> None:
        with self._lock:
            names = list(self._deployments)
        for n in names:
            self.delete(n)

    # ---------------------------------------------------------- introspection
    def get_replicas(self, name: str) -> List[Any]:
        with self._lock:
            rec = self._deployments.get(name)
            return list(rec["replicas"]) if rec else []

    def list_deployments(self) -> Dict[str, int]:
        with self._lock:
            return {n: len(r["replicas"])
                    for n, r in self._deployments.items()}

    # ------------------------------------------------------------- internals
    def _reconcile(self, name: str) -> None:
        from . import replica as rep
        with self._lock:
            rec = self._deployments.get(name)
            if rec is None:
                return
            want = rec["target"]
            have = len(rec["replicas"])
            cls_blob = rec["cls_blob"]
            args, kwargs = rec["init_args"], rec["init_kwargs"]
            opts = dict(rec["actor_options"])
            opts.setdefault("max_concurrency", rec["max_concurrency"])
        while have < want:
            replica = rep.Replica.options(**opts).remote(
                cls_blob, args, kwargs, name)
            with self._lock:
                rec["replicas"].append(replica)
            have += 1
        excess = []
        with self._lock:
            while len(rec["replicas"]) > want:
                excess.append(rec["replicas"].pop())
        self._stop_replicas(excess)

    def _stop_replicas(self, replicas: List[Any]) -> None:
        from .. import kill
        for r in replicas:
            try:
                kill(r)
            except Exception:
                pass

    def _autoscale_loop(self) -> None:
        from .. import get
        while True:
            time.sleep(0.25)
            with self._lock:
                items = [(n, rec) for n, rec in self._deployments.items()
                         if rec.get("autoscaling")]
            for name, rec in items:
                try:
                    cfg = rec["autoscaling"]
                    with self._lock:
                        replicas = list(rec["replicas"])
                    if not replicas:
                        continue
                    depths = get([r.queue_depth.remote()
                                  for r in replicas], timeout=2.0)
                    avg = sum(depths) / len(depths)
                    target_per = cfg.get(
                        "target_num_ongoing_requests_per_replica", 2)
                    want = len(replicas)
                    if avg > target_per:
                        want += 1
                    elif avg < target_per / 2 and want > 1:
                        want -= 1
                    want = max(cfg.get("min_replicas", 1),
                               min(cfg.get("max_replicas", 4), want))
                    if want != len(replicas):
                        with self._lock:
                            rec["target"] = want
                        self._reconcile(name)
                except Exception:
                    continue
