"""Replica actor wrapping the user callable.

Reference: ``serve/_private/replica.py:494`` (RayServeReplica.
handle_request → user callable, queue metrics for autoscaling).

Request observability (ISSUE 13): every request arrives with a compact
context tuple (``spec.request_ctx`` baggage set by the handle, re-bound
by the worker around the call — never an extra arg slot) — the replica
measures queue wait (enqueued_at → execution start),
re-binds the request context around the user callable (and streaming
iteration) so ``serve.get_request_id()`` and ``@serve.batch`` see it,
opens ``request::queue_wait`` / ``request::replica_execute`` spans when
the request is traced, records per-deployment latency/queue-wait
quantile digests, appends one structured access-log row into a
fixed-capacity ring, and promotes slow/error requests to cluster
events through the node's EventLogger (PROFILE_EVENT relay — the
replica worker has no logger of its own). All of it is gated by
``request_log_capacity > 0``; at 0 the request path is the
pre-instrumentation code.
"""

from __future__ import annotations

import inspect
import time
from collections import deque

from .._private import context as _pctx
from .._private import locksan
from .._private import telemetry
from .._private.config import CONFIG
from ..api import remote
from ..util import tracing
from . import request_context as _rc

M_SERVE_LATENCY = telemetry.define(
    "histogram", "rtpu_serve_request_latency_seconds",
    "Replica-side request handling latency, tagged by deployment")
M_SERVE_REQUESTS = telemetry.define(
    "counter", "rtpu_serve_requests_total",
    "Requests handled by serve replicas, tagged deployment and "
    "status=ok|error")
M_SERVE_QUEUE_DEPTH = telemetry.define(
    "gauge", "rtpu_serve_replica_queue_depth",
    "Requests executing + queued on this replica (autoscaling signal)")
M_SERVE_LATENCY_DIGEST = telemetry.define(
    "digest", "rtpu_serve_request_latency_digest_seconds",
    "Streaming quantile digest of replica-side request latency per "
    "deployment (p50/p95/p99 for serve_health and the autoscaler)")
M_SERVE_QUEUE_WAIT_DIGEST = telemetry.define(
    "digest", "rtpu_serve_queue_wait_digest_seconds",
    "Streaming quantile digest of request queue wait (handle routing "
    "enqueue -> replica execution start) per deployment")

# access-log ring rows are stored as compact tuples in this field order
# and shaped into dicts lazily on access_log() reads / slow-error
# promotion — the hot path pays one tuple pack, not a 12-key dict build
_ROW_KEYS = ("ts", "request_id", "deployment", "replica", "route",
             "proto", "model_id", "status", "latency_s", "queue_wait_s",
             "batch_size", "error")


def _shape_row(row: tuple) -> dict:
    d = dict(zip(_ROW_KEYS, row))
    d["latency_s"] = round(d["latency_s"], 6)
    d["queue_wait_s"] = round(d["queue_wait_s"], 6)
    return d


@remote(max_concurrency=8)
class Replica:
    def __init__(self, cls_blob: bytes, init_args: tuple,
                 init_kwargs: dict, deployment_name: str = "",
                 replica_tag: str = ""):
        from .._private import serialization as ser
        target = ser.loads_function(cls_blob)
        if isinstance(target, type):
            self._instance = target(*init_args, **init_kwargs)
        else:
            self._instance = target          # plain function deployment
        self._depth = 0
        self._depth_lock = locksan.lock("serve.replica_depth")
        self._deployment = deployment_name or "default"
        self._replica_tag = replica_tag or "0"
        self._default_route = f"/{self._deployment}"
        self._mtags = (("deployment", self._deployment),)
        self._qtags = self._mtags + (("replica", self._replica_tag),)
        # prebound digest series: two records per request ride these
        # (literal tag tuples, not self._mtags — check_metrics reads
        # the keys statically from the digest_series call site)
        self._lat_digest = telemetry.digest_series(
            M_SERVE_LATENCY_DIGEST, (("deployment", self._deployment),))
        self._wait_digest = telemetry.digest_series(
            M_SERVE_QUEUE_WAIT_DIGEST, (("deployment", self._deployment),))
        # structured access log: fixed-capacity ring, GIL-atomic appends
        # (pool threads share it lock-free); capacity 0 disables the
        # whole request plane
        cap = CONFIG.request_log_capacity
        self._request_log: deque = deque(maxlen=max(cap, 1))
        # worker log lines from this process carry the deployment name
        # instead of a bare worker id (`rtpu logs` greppable by
        # deployment; picked up by the worker runtime at creation)
        self.__rtpu_log_label__ = f"{self._deployment}#{self._replica_tag}"

    def _enter(self) -> None:
        with self._depth_lock:
            self._depth += 1
            depth = self._depth
        telemetry.gauge_set(M_SERVE_QUEUE_DEPTH, float(depth), self._qtags)

    def _exit(self, t0: float, ok: bool) -> None:
        with self._depth_lock:
            self._depth -= 1
            depth = self._depth
        telemetry.gauge_set(M_SERVE_QUEUE_DEPTH, float(depth), self._qtags)
        telemetry.hist_observe(M_SERVE_LATENCY, time.monotonic() - t0,
                               self._mtags)
        telemetry.counter_inc(
            M_SERVE_REQUESTS, 1.0,
            self._mtags + (("status", "ok" if ok else "error"),))

    # ------------------------------------------------ request plane
    def _begin_request(self, req):
        """Measure queue wait, bind the request context, and emit the
        ``request::queue_wait`` span when the request is traced (the
        actor-call span propagated from the ingress is the parent, so
        the whole request shares one trace id). ``req`` is the handle's
        compact wire tuple (request_id, route, proto, enqueued_at,
        model_id); the context dict user code sees is built here.
        Returns the per-request state dict, or None when the plane is
        off."""
        if req is None or not isinstance(req, tuple) or len(req) != 5 \
                or CONFIG._values["request_log_capacity"] <= 0:
            return None
        rid, route, proto, enqueued_at, model_id = req
        # default route/proto ship as None to keep the spec-baggage
        # pickle small (the tuple rides every SUBMIT and EXECUTE frame)
        if route is None:
            route = self._default_route
        if proto is None:
            proto = "python"
        now = time.time()
        queue_wait = now - enqueued_at
        if queue_wait < 0.0:
            # cross-node clock skew hid the wait (enqueued_at is the
            # HANDLE's wall clock): fall back to the skew-free
            # replica-local component — actor-call arrival at this
            # process to execution start. Positive skew inflating the
            # wall measure is undetectable here; keep clocks synced
            # (documented limitation, same tradeoff as the reference's
            # cross-process wall-clock serve metrics).
            recv = _pctx.request_recv_t.get()
            queue_wait = (max(0.0, time.monotonic() - recv)
                          if recv is not None else 0.0)
        telemetry.digest_record(self._wait_digest, queue_wait)
        meta = {"request_id": rid, "deployment": self._deployment,
                "route": route, "proto": proto,
                "enqueued_at": enqueued_at}
        if model_id is not None:
            meta["model_id"] = model_id
        token = _rc.bind(meta)
        parent = tracing.get_current_context()
        traced = parent is not None or tracing.enabled()
        if traced:
            span = tracing.begin_span(
                "request::" + "queue_wait", parent,
                attributes={"request_id": rid,
                            "deployment": self._deployment})
            # the wait ENDED now; it began when the handle enqueued
            span["start_time"] = enqueued_at
            tracing.end_span(span)
        return {"req": meta, "queue_wait": queue_wait, "token": token,
                "traced": traced, "parent": parent,
                "start_wall": now}

    def _exec_span(self, rctx):
        """Only called for TRACED requests (the untraced hot path never
        builds a context manager)."""
        return tracing.start_span(
            "request::" + "replica_execute",
            attributes={"request_id": rctx["req"].get("request_id"),
                        "deployment": self._deployment,
                        "replica": self._replica_tag},
            force=True)

    def _finish_request(self, rctx, t0: float, ok: bool,
                        error=None) -> None:
        if rctx is None:
            return
        token = rctx.pop("token", None)
        if token is not None:
            _rc.unbind(token)
        req = rctx["req"]
        latency = time.monotonic() - t0
        telemetry.digest_record(self._lat_digest, latency)
        row = (time.time(), req.get("request_id"), self._deployment,
               self._replica_tag, req.get("route"), req.get("proto"),
               req.get("model_id"), "ok" if ok else "error", latency,
               rctx["queue_wait"], req.get("batch_size"), error)
        self._request_log.append(row)
        thr = CONFIG._values["serve_slow_request_threshold_s"]
        if not ok or (thr > 0 and latency >= thr):
            self._promote(_shape_row(row), slow=ok)
        # no flush here: the worker's _send_done runs telemetry.
        # maybe_flush AFTER this call's TASK_DONE is on the wire — same
        # shipping cadence, but the (digest-compress + frame) cost
        # lands off the caller's observed latency

    def _promote(self, row: dict, slow: bool) -> None:
        """Relay a slow/error request to the node's EventLogger (the
        literal SLOW_REQUEST/REQUEST_ERROR emit lives node-side — this
        process has no logger)."""
        client = _pctx.current_client
        if client is None:
            return
        what = "slow request" if slow else "request error"
        rec = {
            "kind": "slow" if slow else "error",
            "message": (f"{what} {row.get('request_id')} on "
                        f"{row['deployment']} ({row.get('route')}): "
                        f"latency {row['latency_s']:.3f}s, queue wait "
                        f"{row['queue_wait_s']:.3f}s"
                        + (f" — {row['error']}" if row.get("error")
                           else "")),
            **{k: row.get(k) for k in
               ("request_id", "deployment", "replica", "route",
                "latency_s", "queue_wait_s", "error")},
        }
        try:
            client.send_profile_event("serve_request", rec)
        except Exception:   # noqa: BLE001 — promotion is best-effort
            pass

    def access_log(self, limit: int = 100, slow: bool = False,
                   errors: bool = False):
        """Recent structured request rows from this replica's ring
        (newest last). ``slow`` keeps rows at/over the slow threshold,
        ``errors`` keeps failed rows."""
        # snapshot first: pool threads append concurrently and a deque
        # refuses iteration across a mutation
        rows = [_shape_row(r) for r in list(self._request_log)]
        if errors:
            rows = [r for r in rows if r["status"] == "error"]
        if slow:
            thr = CONFIG.serve_slow_request_threshold_s or 0.0
            rows = [r for r in rows if thr and r["latency_s"] >= thr]
        return rows[-limit:]

    # --------------------------------------------------- request entry
    def handle_request(self, *args, **kwargs):
        # the handle's compact request tuple rides spec.request_ctx and
        # the worker re-binds it around this call — no extra arg slot
        req = _pctx.request_ctx.get()
        self._enter()
        t0 = time.monotonic()
        rctx = self._begin_request(req)
        try:
            if not callable(self._instance):
                raise TypeError("deployment target is not callable")
            if rctx is None or not rctx["traced"]:
                result = self._instance(*args, **kwargs)
            else:
                with self._exec_span(rctx):
                    result = self._instance(*args, **kwargs)
        except BaseException as e:
            self._finish_request(rctx, t0, ok=False, error=repr(e))
            self._exit(t0, ok=False)
            raise
        if inspect.isgenerator(result):
            # streaming: the request is live until the stream drains —
            # record latency/status (and release the queue-depth slot)
            # at exhaustion, not at generator creation. The context
            # token is released here (same thread drives iteration) and
            # re-bound around each step inside the tracker.
            if rctx is not None:
                token = rctx.pop("token", None)
                if token is not None:
                    _rc.unbind(token)
            return self._track_stream(result, t0, rctx)
        self._finish_request(rctx, t0, ok=True)
        self._exit(t0, ok=True)
        return result

    def _track_stream(self, gen, t0: float, rctx=None):
        ok = True
        err = None
        token = _rc.bind(rctx["req"]) if rctx is not None else None
        try:
            yield from gen
        except BaseException as e:
            ok = False
            err = repr(e)
            raise
        finally:
            if token is not None:
                _rc.unbind(token)
            if rctx is not None and rctx.get("traced"):
                # the creation-time replica_execute span closed when
                # the handler RETURNED its generator; the stream's real
                # execution is the drain — emit a stackless span
                # covering it so a traced streaming request's lane
                # shows where the time (and any error) actually went
                span = tracing.begin_span(
                    "request::" + "replica_execute",
                    rctx.get("parent"),
                    attributes={"request_id":
                                rctx["req"].get("request_id"),
                                "deployment": self._deployment,
                                "replica": self._replica_tag,
                                "stream": True})
                span["start_time"] = rctx.get("start_wall",
                                              span["start_time"])
                tracing.end_span(span, error=err)
            self._finish_request(rctx, t0, ok, error=err)
            self._exit(t0, ok)

    def handle_request_mux(self, model_id: str, *args, **kwargs):
        """handle_request with the request's multiplexed model id bound
        for ``serve.get_multiplexed_model_id()`` (reference: proxy sets
        the serve request context's multiplexed_model_id). A streaming
        handler's generator BODY runs lazily during iteration, so the
        binding must wrap the iteration too, not just the call."""
        from .multiplex import (_reset_request_model_id,
                                _set_request_model_id)
        token = _set_request_model_id(model_id)
        try:
            result = self.handle_request(*args, **kwargs)
        finally:
            _reset_request_model_id(token)
        if inspect.isgenerator(result):
            return _iter_with_model_id(model_id, result)
        return result

    def multiplexed_model_ids(self):
        """Model ids currently loaded by any @serve.multiplexed caches
        on this replica (router cache-locality signal)."""
        out = []
        for v in vars(self._instance).values():
            if hasattr(v, "model_ids"):
                try:
                    out.extend(v.model_ids())
                except Exception:   # noqa: BLE001 — introspection only
                    pass
        return out

    def call_method(self, method_name: str, *args, **kwargs):
        self._enter()
        t0 = time.monotonic()
        ok = True
        try:
            return getattr(self._instance, method_name)(*args, **kwargs)
        except BaseException:
            ok = False
            raise
        finally:
            self._exit(t0, ok)

    def queue_depth(self) -> int:
        # executing + queued requests on this replica (approximation of
        # the reference's num_ongoing_requests metric)
        return self._depth

    def reconfigure(self, user_config) -> None:
        if hasattr(self._instance, "reconfigure"):
            self._instance.reconfigure(user_config)


def _iter_with_model_id(model_id: str, gen):
    """Re-bind the request's model id around each step of a streaming
    handler (thread-pooled replicas: per-thread contexts keep this
    isolated between concurrent requests)."""
    from .multiplex import _reset_request_model_id, _set_request_model_id
    token = _set_request_model_id(model_id)
    try:
        yield from gen
    finally:
        _reset_request_model_id(token)
