"""Replica actor wrapping the user callable.

Reference: ``serve/_private/replica.py:494`` (RayServeReplica.
handle_request → user callable, queue metrics for autoscaling).
"""

from __future__ import annotations

import threading

from ..api import remote


@remote(max_concurrency=8)
class Replica:
    def __init__(self, cls_blob: bytes, init_args: tuple,
                 init_kwargs: dict):
        from .._private import serialization as ser
        target = ser.loads_function(cls_blob)
        if isinstance(target, type):
            self._instance = target(*init_args, **init_kwargs)
        else:
            self._instance = target          # plain function deployment
        self._depth = 0
        self._depth_lock = threading.Lock()

    def handle_request(self, *args, **kwargs):
        with self._depth_lock:
            self._depth += 1
        try:
            if not callable(self._instance):
                raise TypeError("deployment target is not callable")
            return self._instance(*args, **kwargs)
        finally:
            with self._depth_lock:
                self._depth -= 1

    def call_method(self, method_name: str, *args, **kwargs):
        with self._depth_lock:
            self._depth += 1
        try:
            return getattr(self._instance, method_name)(*args, **kwargs)
        finally:
            with self._depth_lock:
                self._depth -= 1

    def queue_depth(self) -> int:
        # executing + queued requests on this replica (approximation of
        # the reference's num_ongoing_requests metric)
        return self._depth

    def reconfigure(self, user_config) -> None:
        if hasattr(self._instance, "reconfigure"):
            self._instance.reconfigure(user_config)
