"""Replica actor wrapping the user callable.

Reference: ``serve/_private/replica.py:494`` (RayServeReplica.
handle_request → user callable, queue metrics for autoscaling).
"""

from __future__ import annotations

import time

from .._private import locksan
from .._private import telemetry
from ..api import remote

M_SERVE_LATENCY = telemetry.define(
    "histogram", "rtpu_serve_request_latency_seconds",
    "Replica-side request handling latency, tagged by deployment")
M_SERVE_REQUESTS = telemetry.define(
    "counter", "rtpu_serve_requests_total",
    "Requests handled by serve replicas, tagged deployment and "
    "status=ok|error")
M_SERVE_QUEUE_DEPTH = telemetry.define(
    "gauge", "rtpu_serve_replica_queue_depth",
    "Requests executing + queued on this replica (autoscaling signal)")


@remote(max_concurrency=8)
class Replica:
    def __init__(self, cls_blob: bytes, init_args: tuple,
                 init_kwargs: dict, deployment_name: str = ""):
        from .._private import serialization as ser
        target = ser.loads_function(cls_blob)
        if isinstance(target, type):
            self._instance = target(*init_args, **init_kwargs)
        else:
            self._instance = target          # plain function deployment
        self._depth = 0
        self._depth_lock = locksan.lock("serve.replica_depth")
        self._mtags = (("deployment", deployment_name or "default"),)

    def _enter(self) -> None:
        with self._depth_lock:
            self._depth += 1
            depth = self._depth
        telemetry.gauge_set(M_SERVE_QUEUE_DEPTH, float(depth), self._mtags)

    def _exit(self, t0: float, ok: bool) -> None:
        with self._depth_lock:
            self._depth -= 1
            depth = self._depth
        telemetry.gauge_set(M_SERVE_QUEUE_DEPTH, float(depth), self._mtags)
        telemetry.hist_observe(M_SERVE_LATENCY, time.monotonic() - t0,
                               self._mtags)
        telemetry.counter_inc(
            M_SERVE_REQUESTS, 1.0,
            self._mtags + (("status", "ok" if ok else "error"),))

    def handle_request(self, *args, **kwargs):
        import inspect
        self._enter()
        t0 = time.monotonic()
        try:
            if not callable(self._instance):
                raise TypeError("deployment target is not callable")
            result = self._instance(*args, **kwargs)
        except BaseException:
            self._exit(t0, ok=False)
            raise
        if inspect.isgenerator(result):
            # streaming: the request is live until the stream drains —
            # record latency/status (and release the queue-depth slot)
            # at exhaustion, not at generator creation
            return self._track_stream(result, t0)
        self._exit(t0, ok=True)
        return result

    def _track_stream(self, gen, t0: float):
        ok = True
        try:
            yield from gen
        except BaseException:
            ok = False
            raise
        finally:
            self._exit(t0, ok)

    def handle_request_mux(self, model_id: str, *args, **kwargs):
        """handle_request with the request's multiplexed model id bound
        for ``serve.get_multiplexed_model_id()`` (reference: proxy sets
        the serve request context's multiplexed_model_id). A streaming
        handler's generator BODY runs lazily during iteration, so the
        binding must wrap the iteration too, not just the call."""
        import inspect

        from .multiplex import (_reset_request_model_id,
                                _set_request_model_id)
        token = _set_request_model_id(model_id)
        try:
            result = self.handle_request(*args, **kwargs)
        finally:
            _reset_request_model_id(token)
        if inspect.isgenerator(result):
            return _iter_with_model_id(model_id, result)
        return result

    def multiplexed_model_ids(self):
        """Model ids currently loaded by any @serve.multiplexed caches
        on this replica (router cache-locality signal)."""
        out = []
        for v in vars(self._instance).values():
            if hasattr(v, "model_ids"):
                try:
                    out.extend(v.model_ids())
                except Exception:   # noqa: BLE001 — introspection only
                    pass
        return out

    def call_method(self, method_name: str, *args, **kwargs):
        self._enter()
        t0 = time.monotonic()
        ok = True
        try:
            return getattr(self._instance, method_name)(*args, **kwargs)
        except BaseException:
            ok = False
            raise
        finally:
            self._exit(t0, ok)

    def queue_depth(self) -> int:
        # executing + queued requests on this replica (approximation of
        # the reference's num_ongoing_requests metric)
        return self._depth

    def reconfigure(self, user_config) -> None:
        if hasattr(self._instance, "reconfigure"):
            self._instance.reconfigure(user_config)


def _iter_with_model_id(model_id: str, gen):
    """Re-bind the request's model id around each step of a streaming
    handler (thread-pooled replicas: per-thread contexts keep this
    isolated between concurrent requests)."""
    from .multiplex import _reset_request_model_id, _set_request_model_id
    token = _set_request_model_id(model_id)
    try:
        yield from gen
    finally:
        _reset_request_model_id(token)
