"""Replica actor wrapping the user callable.

Reference: ``serve/_private/replica.py:494`` (RayServeReplica.
handle_request → user callable, queue metrics for autoscaling).
"""

from __future__ import annotations

import threading

from ..api import remote


@remote(max_concurrency=8)
class Replica:
    def __init__(self, cls_blob: bytes, init_args: tuple,
                 init_kwargs: dict):
        from .._private import serialization as ser
        target = ser.loads_function(cls_blob)
        if isinstance(target, type):
            self._instance = target(*init_args, **init_kwargs)
        else:
            self._instance = target          # plain function deployment
        self._depth = 0
        self._depth_lock = threading.Lock()

    def handle_request(self, *args, **kwargs):
        with self._depth_lock:
            self._depth += 1
        try:
            if not callable(self._instance):
                raise TypeError("deployment target is not callable")
            return self._instance(*args, **kwargs)
        finally:
            with self._depth_lock:
                self._depth -= 1

    def handle_request_mux(self, model_id: str, *args, **kwargs):
        """handle_request with the request's multiplexed model id bound
        for ``serve.get_multiplexed_model_id()`` (reference: proxy sets
        the serve request context's multiplexed_model_id). A streaming
        handler's generator BODY runs lazily during iteration, so the
        binding must wrap the iteration too, not just the call."""
        import inspect

        from .multiplex import (_reset_request_model_id,
                                _set_request_model_id)
        token = _set_request_model_id(model_id)
        try:
            result = self.handle_request(*args, **kwargs)
        finally:
            _reset_request_model_id(token)
        if inspect.isgenerator(result):
            return _iter_with_model_id(model_id, result)
        return result

    def multiplexed_model_ids(self):
        """Model ids currently loaded by any @serve.multiplexed caches
        on this replica (router cache-locality signal)."""
        out = []
        for v in vars(self._instance).values():
            if hasattr(v, "model_ids"):
                try:
                    out.extend(v.model_ids())
                except Exception:   # noqa: BLE001 — introspection only
                    pass
        return out

    def call_method(self, method_name: str, *args, **kwargs):
        with self._depth_lock:
            self._depth += 1
        try:
            return getattr(self._instance, method_name)(*args, **kwargs)
        finally:
            with self._depth_lock:
                self._depth -= 1

    def queue_depth(self) -> int:
        # executing + queued requests on this replica (approximation of
        # the reference's num_ongoing_requests metric)
        return self._depth

    def reconfigure(self, user_config) -> None:
        if hasattr(self._instance, "reconfigure"):
            self._instance.reconfigure(user_config)


def _iter_with_model_id(model_id: str, gen):
    """Re-bind the request's model id around each step of a streaming
    handler (thread-pooled replicas: per-thread contexts keep this
    isolated between concurrent requests)."""
    from .multiplex import _reset_request_model_id, _set_request_model_id
    token = _set_request_model_id(model_id)
    try:
        yield from gen
    finally:
        _reset_request_model_id(token)
