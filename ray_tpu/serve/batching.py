"""@serve.batch — dynamic request batching inside a replica.

Reference: ``python/ray/serve/batching.py`` (``@serve.batch`` queues
concurrent calls, fires the underlying function once per batch).
Implementation: a per-function collector thread gathers requests until
``max_batch_size`` or ``batch_wait_timeout_s`` and invokes the wrapped
callable with the list; callers block on their slot's future. Works with
threaded actors (``max_concurrency > 1``) — concurrency is what creates
batchable simultaneous requests.
"""

from __future__ import annotations

import functools
import queue as _queue
import threading
import time as _time

from .._private import locksan
from .._private import telemetry
from concurrent.futures import Future
from typing import Any, Callable, List, Optional

from . import request_context as _rc

M_SERVE_BATCH_SIZE_DIGEST = telemetry.define(
    "digest", "rtpu_serve_batch_size_digest",
    "Streaming quantile digest of @serve.batch batch sizes per "
    "deployment (how well concurrent requests coalesce)")


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self.q: "_queue.Queue" = _queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = locksan.lock("serve.batcher")

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()

    def _loop(self):
        while True:
            item = self.q.get()          # (arg, future, req_meta, trace)
            t_first = _time.monotonic()
            batch = [item]
            # absolute deadline per batch: a fixed per-get timeout would
            # reset on every arrival, making the first caller wait up to
            # (max_batch_size-1)*timeout under a trickle of requests
            deadline = t_first + self.timeout_s
            while len(batch) < self.max_batch_size:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self.q.get(timeout=remaining))
                except _queue.Empty:
                    break
            args = [it[0] for it in batch]
            futures = [it[1] for it in batch]
            try:
                # accounting must never break the batch: an exception
                # here (thread exhaustion in a lazy flusher start,
                # interpreter teardown) would kill the collector with
                # every member's future unresolved — callers block on
                # fut.result() with no timeout
                self._note_batch(batch, t_first)
            except Exception:   # noqa: BLE001 — observability only
                pass
            # bind the batch LEADER's request context around the user
            # function: one invocation serves N requests, so a single
            # id is inherently approximate, but get_request_id() inside
            # a batched body should name a member of THIS batch, not ""
            # (the per-member ids live in each access-log row)
            lead = next((it[2] for it in batch
                         if len(it) > 2 and it[2]), None)
            tok = _rc.bind(lead) if lead is not None else None
            try:
                results = self.fn(args)
                if results is None or len(results) != len(args):
                    raise ValueError(
                        "@serve.batch function must return one result per "
                        f"input ({len(args)} inputs)")
                for fut, res in zip(futures, results):
                    fut.set_result(res)
            except Exception as e:
                for fut in futures:
                    fut.set_exception(e)
            finally:
                if tok is not None:
                    _rc.unbind(tok)

    @staticmethod
    def _note_batch(batch, t_first: float) -> None:
        """Request-plane accounting for one assembled batch: stamp each
        member request's batch size (the replica's access-log row reads
        it back), record the per-deployment batch-size digest, and emit
        one ``request::batch_assemble`` span parented to the first
        member's trace (span start = first arrival, end = invoke)."""
        metas = [it[2] for it in batch if len(it) > 2 and it[2]]
        if not metas:
            return                    # plane off / outside a request
        n = len(batch)
        for meta in metas:
            meta["batch_size"] = n
        deployment = metas[0].get("deployment", "default")
        telemetry.digest_observe(M_SERVE_BATCH_SIZE_DIGEST, float(n),
                                 (("deployment", deployment),))
        from ..util import tracing
        parent = next((it[3] for it in batch
                       if len(it) > 3 and it[3]), None)
        if parent is not None or tracing.enabled():
            span = tracing.begin_span(
                "request::" + "batch_assemble", parent,
                attributes={"deployment": deployment, "batch_size": n,
                            "request_id": metas[0].get("request_id")})
            wait = _time.monotonic() - t_first
            span["start_time"] = _time.time() - wait
            tracing.end_span(span)

    def submit(self, arg: Any) -> Any:
        self._ensure_thread()
        fut: Future = Future()
        # carry the caller's request context + trace ctx to the
        # collector thread (contextvars/thread-locals don't cross)
        meta = _rc.current() if _rc.enabled() else None
        trace = None
        if meta is not None:
            from ..util import tracing
            trace = tracing.get_current_context()
        self.q.put((arg, fut, meta, trace))
        return fut.result()


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorate a method taking a LIST of requests; singular calls are
    coalesced into batches transparently."""

    def decorator(fn):
        attr = f"__rtpu_batcher_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(self, request):
            b = getattr(self, attr, None)
            if b is None:
                b = _Batcher(lambda args: fn(self, args), max_batch_size,
                             batch_wait_timeout_s)
                setattr(self, attr, b)
            return b.submit(request)

        wrapper._rtpu_is_batched = True
        return wrapper

    if _fn is not None:
        return decorator(_fn)
    return decorator
