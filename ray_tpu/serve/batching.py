"""@serve.batch — dynamic request batching inside a replica.

Reference: ``python/ray/serve/batching.py`` (``@serve.batch`` queues
concurrent calls, fires the underlying function once per batch).
Implementation: a per-function collector thread gathers requests until
``max_batch_size`` or ``batch_wait_timeout_s`` and invokes the wrapped
callable with the list; callers block on their slot's future. Works with
threaded actors (``max_concurrency > 1``) — concurrency is what creates
batchable simultaneous requests.
"""

from __future__ import annotations

import functools
import queue as _queue
import threading
import time as _time

from .._private import locksan
from concurrent.futures import Future
from typing import Any, Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self.q: "_queue.Queue" = _queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = locksan.lock("serve.batcher")

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()

    def _loop(self):
        while True:
            item = self.q.get()          # (arg, future)
            batch = [item]
            # absolute deadline per batch: a fixed per-get timeout would
            # reset on every arrival, making the first caller wait up to
            # (max_batch_size-1)*timeout under a trickle of requests
            deadline = _time.monotonic() + self.timeout_s
            while len(batch) < self.max_batch_size:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self.q.get(timeout=remaining))
                except _queue.Empty:
                    break
            args = [a for a, _ in batch]
            futures = [f for _, f in batch]
            try:
                results = self.fn(args)
                if results is None or len(results) != len(args):
                    raise ValueError(
                        "@serve.batch function must return one result per "
                        f"input ({len(args)} inputs)")
                for fut, res in zip(futures, results):
                    fut.set_result(res)
            except Exception as e:
                for fut in futures:
                    fut.set_exception(e)

    def submit(self, arg: Any) -> Any:
        self._ensure_thread()
        fut: Future = Future()
        self.q.put((arg, fut))
        return fut.result()


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorate a method taking a LIST of requests; singular calls are
    coalesced into batches transparently."""

    def decorator(fn):
        attr = f"__rtpu_batcher_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(self, request):
            b = getattr(self, attr, None)
            if b is None:
                b = _Batcher(lambda args: fn(self, args), max_batch_size,
                             batch_wait_timeout_s)
                setattr(self, attr, b)
            return b.submit(request)

        wrapper._rtpu_is_batched = True
        return wrapper

    if _fn is not None:
        return decorator(_fn)
    return decorator
