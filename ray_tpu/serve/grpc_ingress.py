"""gRPC ingress for Serve.

Reference: ``serve/_private/proxy.py:613`` gRPCProxy + the
``serve/generated/serve_pb2_grpc`` service. Here the service is a
GENERIC gRPC handler (no compiled protos — the image carries grpcio
but not protoc-generated stubs): JSON-bytes in/out on two methods,

* ``/rtpu.serve.Ingress/Call``   unary-unary   {"deployment", "arg",
  "multiplexed_model_id"?} -> {"result"} | {"error"}
* ``/rtpu.serve.Ingress/Stream`` unary-stream  same request, one JSON
  frame per produced item, terminal {"error"} frame on mid-stream
  failure (mirrors the HTTP NDJSON contract).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

SERVICE = "rtpu.serve.Ingress"


def _handler(gateway):
    import grpc

    def _parse(data: bytes):
        try:
            req = json.loads(data or b"{}")
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as e:
            return None, {}, json.dumps(
                {"error": f"bad request: {e}"}).encode()
        name = req.get("deployment")
        if not name or f"/{name}" not in gateway.routes():
            return None, req, json.dumps(
                {"error": f"no deployment {name!r}"}).encode()
        return name, req, None

    def call(data: bytes, context) -> bytes:
        name, req, err = _parse(data)
        if err is not None:
            return err
        try:
            # a caller-supplied "request_id" is honored (mirrors the
            # HTTP X-Request-ID contract)
            result = gateway.call(name, req.get("arg"),
                                  model_id=req.get(
                                      "multiplexed_model_id"),
                                  request_id=req.get("request_id"),
                                  proto="grpc")
            return json.dumps({"result": result}).encode()
        except Exception as e:   # noqa: BLE001 — wire errors as JSON
            return json.dumps({"error": str(e)}).encode()

    def stream(data: bytes, context):
        name, req, err = _parse(data)
        if err is not None:
            yield err
            return
        try:
            it = gateway.stream(name, req.get("arg"),
                                model_id=req.get(
                                    "multiplexed_model_id"),
                                request_id=req.get("request_id"),
                                proto="grpc")
            for item in it:
                yield json.dumps({"item": item}).encode()
        except Exception as e:   # noqa: BLE001 — terminal error frame
            yield json.dumps({"error": str(e)}).encode()

    ident = lambda b: b          # noqa: E731 — bytes in, bytes out
    return grpc.method_handlers_generic_handler(SERVICE, {
        "Call": grpc.unary_unary_rpc_method_handler(
            call, request_deserializer=ident, response_serializer=ident),
        "Stream": grpc.unary_stream_rpc_method_handler(
            stream, request_deserializer=ident,
            response_serializer=ident),
    })


def start_grpc(host: str = "127.0.0.1", port: int = 0):
    """Start the gRPC ingress; returns (server, "host:port")."""
    from concurrent import futures

    import grpc

    from .api import _GatewayHandler

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    server.add_generic_rpc_handlers((_handler(_GatewayHandler()),))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise RuntimeError(f"could not bind gRPC ingress on "
                           f"{host}:{port}")
    server.start()
    return server, f"{host}:{bound}"


# ---------------------------------------------------------- client side

def grpc_call(address: str, deployment: str, arg: Any = None, *,
              multiplexed_model_id: Optional[str] = None,
              timeout: float = 30.0) -> Dict[str, Any]:
    """Convenience unary client (tests/CLIs; any gRPC client works)."""
    import grpc

    req: Dict[str, Any] = {"deployment": deployment, "arg": arg}
    if multiplexed_model_id:
        req["multiplexed_model_id"] = multiplexed_model_id
    with grpc.insecure_channel(address) as ch:
        fn = ch.unary_unary(f"/{SERVICE}/Call",
                            request_serializer=lambda b: b,
                            response_deserializer=lambda b: b)
        return json.loads(fn(json.dumps(req).encode(), timeout=timeout))


def grpc_stream(address: str, deployment: str, arg: Any = None, *,
                multiplexed_model_id: Optional[str] = None,
                timeout: float = 60.0):
    """Convenience streaming client: yields decoded item frames."""
    import grpc

    req: Dict[str, Any] = {"deployment": deployment, "arg": arg}
    if multiplexed_model_id:
        req["multiplexed_model_id"] = multiplexed_model_id
    with grpc.insecure_channel(address) as ch:
        fn = ch.unary_stream(f"/{SERVICE}/Stream",
                             request_serializer=lambda b: b,
                             response_deserializer=lambda b: b)
        for frame in fn(json.dumps(req).encode(), timeout=timeout):
            yield json.loads(frame)
