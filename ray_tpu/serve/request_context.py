"""Per-request serve context: request ids and their propagation.

Reference analogue: ``serve/_private/request_context.py`` — every
request entering Serve gets a request id carried in a contextvar
through proxy → router → replica, readable from user code via
``serve.get_request_id()``. Here the context is a plain mutable dict
(request_id, deployment, route, proto, enqueued_at, optionally
model_id/batch_size) that the ingress creates, the handle ships to the
replica as a reserved kwarg, and the replica re-binds around the user
callable (and around streaming iteration) — so nested ``@serve.batch``
collectors and user code observe the request they serve.

The whole plane is gated by ``request_log_capacity > 0``: at 0 no
request metadata attaches anywhere and the request path is exactly the
pre-instrumentation code.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import time
from typing import Any, Dict, Optional

from .._private.config import CONFIG

_current: "contextvars.ContextVar[Optional[Dict[str, Any]]]" = \
    contextvars.ContextVar("rtpu_serve_request", default=None)

# request-id = 8 random hex (per process, drawn once) + 8 hex counter:
# globally unique without an os.urandom syscall per request (ids are
# minted on the request hot path)
_rid_prefix = os.urandom(4).hex()
_rid_counter = itertools.count(1)


def enabled() -> bool:
    # direct _values read: this gates every handle call (both arms of
    # the request_ab gate) and __getattr__ dispatch costs ~0.4µs
    return CONFIG._values["request_log_capacity"] > 0


def new_request_id() -> str:
    return f"{_rid_prefix}{next(_rid_counter) & 0xffffffff:08x}"


def make(deployment: str, route: Optional[str] = None,
         request_id: Optional[str] = None,
         proto: str = "python") -> Dict[str, Any]:
    """A fresh request context dict (the ingress entry point)."""
    return {
        "request_id": request_id or new_request_id(),
        "deployment": deployment,
        "route": route or f"/{deployment}",
        "proto": proto,
        "enqueued_at": time.time(),
    }


def current() -> Optional[Dict[str, Any]]:
    return _current.get()


def get_request_id() -> str:
    """Inside a deployment handler (or any code on the request path):
    the current request's id, or "" outside a request."""
    ctx = _current.get()
    return (ctx or {}).get("request_id", "")


def bind(meta: Optional[Dict[str, Any]]):
    return _current.set(meta)


def unbind(token) -> None:
    _current.reset(token)
