"""Model multiplexing: many models share one replica pool.

Reference: ``python/ray/serve/multiplex.py`` (_ModelMultiplexWrapper —
per-replica LRU of loaded models keyed by model id, evicting beyond
``max_num_models_per_replica``) and ``serve/api.py``
``get_multiplexed_model_id``. Requests carry the model id through
``handle.options(multiplexed_model_id=...)``; the handle routes
requests for one model to the replica that already loaded it (cache
locality), and the replica's wrapper loads/evicts on demand.
"""

from __future__ import annotations

import contextvars

from .._private import locksan
from collections import OrderedDict
from typing import Any, Callable, Optional

_current_model_id: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("rtpu_serve_model_id", default=None)


def get_multiplexed_model_id() -> str:
    """Inside a deployment handler: the model id of the current request
    (empty string when the request carried none)."""
    return _current_model_id.get() or ""


def _set_request_model_id(model_id: Optional[str]):
    return _current_model_id.set(model_id)


def _reset_request_model_id(token) -> None:
    _current_model_id.reset(token)


class _MultiplexCache:
    """Per-replica-instance LRU of loaded models."""

    def __init__(self, loader: Callable, max_models: int):
        self._loader = loader
        self._max = max_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = locksan.lock("serve.multiplex")

    def get(self, instance, model_id: str):
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
        # load OUTSIDE the lock (loads can be slow); a racing duplicate
        # load is wasted work, not an error
        model = self._loader(instance, model_id)
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            while len(self._models) > self._max:
                _, old = self._models.popitem(last=False)
                # cooperative unload hook; NOT __del__ (invoking a
                # finalizer directly would run it again at GC time)
                unload = getattr(old, "unload", None)
                if callable(unload):
                    try:
                        unload()
                    except Exception:   # noqa: BLE001 — eviction is
                        pass            # best-effort
        return model

    def model_ids(self):
        with self._lock:
            return list(self._models)


def multiplexed(max_num_models_per_replica: int = 3):
    """Decorator for a deployment method ``def get_model(self, model_id)``
    that loads one model; calls are LRU-cached per replica up to
    ``max_num_models_per_replica`` (reference: ``serve.multiplexed``)."""
    if max_num_models_per_replica < 1:
        raise ValueError("max_num_models_per_replica must be >= 1")

    def deco(fn: Callable):
        cache_attr = f"__rtpu_mux_{fn.__name__}"

        def wrapper(self, model_id: str):
            cache = getattr(self, cache_attr, None)
            if cache is None:
                cache = _MultiplexCache(fn, max_num_models_per_replica)
                setattr(self, cache_attr, cache)
            return cache.get(self, model_id)

        wrapper.__name__ = fn.__name__
        wrapper.__rtpu_multiplexed__ = cache_attr
        return wrapper
    return deco
