"""DeploymentHandle — client-side router with power-of-two-choices.

Reference: ``serve/_private/router.py:944`` (Router) + ``:330``
(PowerOfTwoChoicesReplicaScheduler): pick two random replicas, send to
the one with the shorter queue. Queue lengths here are tracked
client-side per handle (in-flight counter per replica), refreshed with
the controller's replica list on a TTL.
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional

from .. import get
from .._private import context as _pctx
from .._private import locksan
from . import request_context as _rc

_REFRESH_S = 1.0


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller):
        self.deployment_name = deployment_name
        self._default_route = f"/{deployment_name}"
        self._controller = controller
        self._replicas: List[Any] = []
        self._inflight: Dict[int, int] = {}
        # multiplexing cache locality: model_id -> replica index that
        # loaded it last (reference: router prefers replicas whose
        # multiplexed-model cache holds the request's model)
        self._model_affinity: Dict[str, int] = {}
        self._last_refresh = 0.0
        self._lock = locksan.lock("serve.handle")
        self._rng = random.Random()

    # -------------------------------------------------------------- routing
    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_refresh < _REFRESH_S:
            return
        replicas = get(self._controller.get_replicas.remote(
            self.deployment_name))
        def ids(rs):
            return [getattr(r, "_actor_id", None) for r in rs]

        with self._lock:
            if ids(replicas) != ids(self._replicas):
                # the replica SET changed (stable actor ids — fresh
                # handle objects deserialize per poll): indices shifted,
                # cached model->replica affinities point at the wrong
                # replicas now
                self._model_affinity.clear()
            self._replicas = replicas
            self._inflight = {i: self._inflight.get(i, 0)
                              for i in range(len(replicas))}
            self._last_refresh = now

    def _pick(self) -> int:
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError(
                    f"deployment {self.deployment_name!r} has no replicas")
            if n == 1:
                idx = 0
            else:
                a, b = self._rng.sample(range(n), 2)
                idx = a if self._inflight.get(a, 0) <= \
                    self._inflight.get(b, 0) else b
            self._inflight[idx] = self._inflight.get(idx, 0) + 1
            return idx

    def _done(self, idx: int) -> None:
        with self._lock:
            if idx in self._inflight and self._inflight[idx] > 0:
                self._inflight[idx] -= 1

    def _pick_for_model(self, model_id: str) -> int:
        """Prefer the replica that already holds this model (LRU cache
        locality); fall back to power-of-two and remember the choice."""
        with self._lock:
            idx = self._model_affinity.get(model_id)
            if idx is not None and idx < len(self._replicas):
                self._inflight[idx] = self._inflight.get(idx, 0) + 1
                return idx
        idx = self._pick()
        with self._lock:
            if len(self._model_affinity) >= 256:
                self._model_affinity.pop(
                    next(iter(self._model_affinity)))
            self._model_affinity[model_id] = idx
        return idx

    def options(self, *, multiplexed_model_id: Optional[str] = None
                ) -> "DeploymentHandle":
        """Per-request routing options (reference:
        ``handle.options(multiplexed_model_id=...)``)."""
        if multiplexed_model_id is None:
            return self
        return _ModelBoundHandle(self, multiplexed_model_id)

    # ---------------------------------------------------------------- calls
    def remote(self, *args, **kwargs):
        """Route one request; returns an ObjectRef."""
        return self._route(None, *args, **kwargs)

    def _request_meta(self, model_id) -> Optional[tuple]:
        """Request metadata shipped to the replica in
        ``spec.request_ctx``: the ingress context when one is bound
        (HTTP/gRPC gateways), a fresh one otherwise (plain Python
        callers) — every request gets an id. ``enqueued_at`` is stamped
        HERE so the replica's queue-wait measurement covers routing +
        actor-call queueing. A compact TUPLE riding INSIDE the one spec
        pickle stream — NOT an extra arg slot, which costs a separate
        pickle + load per call (the request_ab overhead gate prices
        this path)."""
        if not _rc.enabled():
            return None
        ctx = _rc.current()
        if ctx is not None:
            # default route/proto ship as None (replica reconstructs):
            # the tuple is pickled on every SUBMIT and EXECUTE frame
            route = ctx.get("route")
            if route == self._default_route:
                route = None
            proto = ctx.get("proto", "python")
            return (ctx.get("request_id") or _rc.new_request_id(),
                    route,
                    None if proto == "python" else proto,
                    time.time(), model_id)
        return (_rc.new_request_id(), None, None, time.time(), model_id)

    def _route(self, model_id, *args, **kwargs):
        self._refresh()
        meta = self._request_meta(model_id)
        token = (_pctx.request_ctx.set(meta)
                 if meta is not None else None)
        try:
            for attempt in range(3):
                idx = (self._pick() if model_id is None
                       else self._pick_for_model(model_id))
                with self._lock:
                    replica = self._replicas[idx]
                try:
                    if model_id is None:
                        ref = replica.handle_request.remote(*args,
                                                            **kwargs)
                    else:
                        ref = replica.handle_request_mux.remote(
                            model_id, *args, **kwargs)
                except Exception:
                    self._done(idx)
                    with self._lock:
                        if self._model_affinity.get(model_id) == idx:
                            del self._model_affinity[model_id]
                    self._refresh(force=True)
                    continue
                # in-flight slot released when the response is consumed
                return _TrackedRef(ref, self, idx)
            raise RuntimeError("no live replica accepted the request")
        finally:
            if token is not None:
                _pctx.request_ctx.reset(token)

    def stream(self, *args, **kwargs):
        """Route one STREAMING request: the deployment's handler must
        return a generator, whose items arrive as they are produced
        (reference: Serve streaming responses over ObjectRefGenerator).
        Returns an iterator of item VALUES."""
        return self._route_stream(None, *args, **kwargs)

    def _route_stream(self, model_id, *args, **kwargs):
        self._refresh()
        meta = self._request_meta(model_id)
        token = (_pctx.request_ctx.set(meta)
                 if meta is not None else None)
        try:
            for attempt in range(3):
                idx = (self._pick() if model_id is None
                       else self._pick_for_model(model_id))
                with self._lock:
                    replica = self._replicas[idx]
                try:
                    if model_id is None:
                        gen = replica.handle_request.options(
                            num_returns="streaming").remote(*args,
                                                            **kwargs)
                    else:
                        gen = replica.handle_request_mux.options(
                            num_returns="streaming").remote(
                                model_id, *args, **kwargs)
                except Exception:
                    self._done(idx)
                    with self._lock:
                        if self._model_affinity.get(model_id) == idx:
                            del self._model_affinity[model_id]
                    self._refresh(force=True)
                    continue
                return _TrackedStream(gen, self, idx)
            raise RuntimeError("no live replica accepted the request")
        finally:
            if token is not None:
                _pctx.request_ctx.reset(token)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name, self._controller))


class _ModelBoundHandle:
    """A DeploymentHandle view with a fixed multiplexed model id."""

    def __init__(self, handle: DeploymentHandle, model_id: str):
        self._handle = handle
        self._model_id = model_id

    def remote(self, *args, **kwargs):
        return self._handle._route(self._model_id, *args, **kwargs)

    def stream(self, *args, **kwargs):
        return self._handle._route_stream(self._model_id,
                                          *args, **kwargs)

    def options(self, *, multiplexed_model_id: Optional[str] = None):
        if multiplexed_model_id is None:
            return self
        return _ModelBoundHandle(self._handle, multiplexed_model_id)

    def __getattr__(self, name):
        return getattr(self._handle, name)


class _TrackedStream:
    """Iterates a streaming response's values; releases the replica's
    in-flight slot when the stream ends (or is dropped)."""

    def __init__(self, gen, handle: "DeploymentHandle", idx: int):
        self._gen = gen
        self._handle = handle
        self._idx = idx
        self._released = False

    def __iter__(self):
        return self

    def __next__(self):
        try:
            ref = next(self._gen)
        except BaseException:
            self._release()
            raise
        return get(ref)

    def _release(self) -> None:
        if not self._released:
            self._released = True
            self._handle._done(self._idx)

    def __del__(self):
        self._release()


class _TrackedRef:
    """ObjectRef wrapper that releases the in-flight slot on result()."""

    def __init__(self, ref, handle: DeploymentHandle, idx: int):
        self._ref = ref
        self._handle = handle
        self._idx = idx
        self._resolved = False

    @property
    def ref(self):
        return self._ref

    def result(self, timeout: Optional[float] = None):
        try:
            return get(self._ref, timeout=timeout)
        finally:
            if not self._resolved:
                self._resolved = True
                self._handle._done(self._idx)
