"""ray_tpu.serve — model serving on actors.

Reference: Ray Serve (``python/ray/serve/``, SURVEY §2.3/§3.5): a
controller actor reconciles declarative deployment state into replica
actors; handles/proxies route requests with power-of-two-choices on
queue length; autoscaling reacts to queue metrics; ``@serve.batch``
coalesces concurrent requests for batched inference — the essential
feature for TPU replicas, where batch = MXU utilization.

Surface: ``@serve.deployment`` → ``serve.run(app)`` → handle, plus an
optional stdlib HTTP gateway (``serve.start_http``).
"""

from .api import (  # noqa: F401
    delete,
    deployment,
    get_deployment_handle,
    run,
    shutdown,
    start,
    start_grpc,
    start_http,
    stop_grpc,
    stop_http,
)
from .grpc_ingress import grpc_call, grpc_stream  # noqa: F401
from .batching import batch  # noqa: F401
from .handle import DeploymentHandle  # noqa: F401
from .multiplex import (  # noqa: F401
    get_multiplexed_model_id,
    multiplexed,
)
from .proxy import proxy_addresses  # noqa: F401
from .request_context import get_request_id  # noqa: F401
