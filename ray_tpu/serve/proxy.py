"""Per-node HTTP proxies.

Reference: ``serve/_private/proxy_state.py`` (ProxyStateManager — the
controller keeps one HTTPProxy actor alive per cluster node) +
``proxy.py:613`` (HTTPProxy). Here each proxy is a detached actor
pinned to its node with NodeAffinity, running the same JSON/NDJSON
gateway the head's ``serve.start_http`` runs; any node's port serves
every deployment (routing state comes from the controller, which is
location-transparent).

Request observability rides along for free: the shared
``_GatewayHandler`` mints (or adopts, via ``X-Request-ID``) a request
id per request, opens the ``request::ingress`` span, and binds the
request context the handle ships to the replica — so a request through
ANY node's proxy traces and logs identically to one through the head
gateway. The proxy actor's own log lines carry its node in the worker
prefix; replica lines carry their deployment name.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import get, get_actor, kill
from ..api import remote
from .._private.scheduler import NodeAffinitySchedulingStrategy

_PROXY_PREFIX = "SERVE_PROXY:"


@remote(num_cpus=0, max_concurrency=8)
class ProxyActor:
    """One node's HTTP ingress. Runs the gateway HTTP server in this
    actor's process; the bound address is queryable."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 grpc_port: Optional[int] = 0):
        import socket

        from .api import _gateway_server
        self._server, self._addr = _gateway_server(host, port)
        # gRPC side-by-side (reference: proxies serve both protocols);
        # None disables it
        self._grpc_server = None
        self._grpc_addr = None
        if grpc_port is not None:
            from .grpc_ingress import start_grpc
            self._grpc_server, self._grpc_addr = start_grpc(host,
                                                            grpc_port)
        if host == "0.0.0.0":
            # a wildcard bind is not a connectable URL; advertise this
            # node's resolvable address instead (multi-host ingress —
            # loopback binds stay loopback, as configured)
            try:
                ip = socket.gethostbyname(socket.gethostname())
                self._addr = self._addr.replace("0.0.0.0", ip)
            except OSError:
                pass

    def address(self) -> str:
        return self._addr

    def grpc_address(self) -> Optional[str]:
        return self._grpc_addr

    def ready(self) -> bool:
        return True

    def stop(self) -> None:
        self._server.stop()
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=None)


def _alive_nodes() -> List[dict]:
    from ..state.api import list_nodes
    return [n for n in list_nodes() if n.get("alive")]


def ensure_proxies(host: str = "127.0.0.1",
                   port: int = 0) -> Dict[str, str]:
    """Reconcile one proxy per alive node (reference:
    ``ProxyStateManager.update``); returns {node_id_hex: address}.
    Idempotent — existing proxies are kept, new nodes get one."""
    out: Dict[str, str] = {}
    for node in _alive_nodes():
        node_id = node["node_id"]
        name = _PROXY_PREFIX + node_id.hex()
        try:
            proxy = get_actor(name)
        except ValueError:
            proxy = ProxyActor.options(
                name=name, lifetime="detached",
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id, soft=False),
            ).remote(host, port)
        out[node_id.hex()] = get(proxy.address.remote(), timeout=30)
    return out


def proxy_addresses() -> Dict[str, str]:
    """Addresses of currently-live proxies (no reconciliation)."""
    out: Dict[str, str] = {}
    for node in _alive_nodes():
        node_hex = node["node_id"].hex()
        try:
            proxy = get_actor(_PROXY_PREFIX + node_hex)
            out[node_hex] = get(proxy.address.remote(), timeout=5)
        except Exception:   # noqa: BLE001 — absent proxy = no entry
            continue
    return out


def stop_proxies() -> None:
    for node in _alive_nodes():
        try:
            proxy = get_actor(_PROXY_PREFIX + node["node_id"].hex())
        except ValueError:
            continue
        try:
            get(proxy.stop.remote(), timeout=5)
        except Exception:   # noqa: BLE001 — dying proxy is fine
            pass
        try:
            kill(proxy)
        except Exception:   # noqa: BLE001
            pass
