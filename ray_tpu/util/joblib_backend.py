"""joblib backend running jobs as cluster tasks.

Reference analogue: ``python/ray/util/joblib/`` — ``register_ray()``
plugs a ParallelBackend into joblib so scikit-learn style
``Parallel(n_jobs=...)`` fan-outs run on the cluster:

    from ray_tpu.util.joblib_backend import register_rtpu
    register_rtpu()
    with joblib.parallel_backend("rtpu"):
        Parallel(n_jobs=8)(delayed(f)(i) for i in range(100))
"""

from __future__ import annotations

from typing import Any, Callable, List

import ray_tpu
from .._private import serialization as _ser


@ray_tpu.remote
def _run_batch(batch_blob: bytes) -> Any:
    # cloudpickle by value: joblib's BatchedCalls closes over user
    # callables that workers cannot import by module path
    return _ser.loads_function(batch_blob)()


def register_rtpu() -> None:
    """Register the ``"rtpu"`` joblib parallel backend."""
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("rtpu", _RtpuBackend)


try:
    from joblib._parallel_backends import ParallelBackendBase
except Exception:  # pragma: no cover — joblib ships in the image
    ParallelBackendBase = object


class _RtpuBackend(ParallelBackendBase):
    """Each joblib batch (a callable of pre-bound work items) becomes
    one remote task; joblib's own batching controls granularity."""

    supports_timeout = True
    uses_threads = False
    supports_sharedmem = False

    def __init__(self, *args, **kwargs):
        if ParallelBackendBase is not object:
            super().__init__(*args, **kwargs)

    def configure(self, n_jobs: int = 1, parallel=None, **kwargs) -> int:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.parallel = parallel
        return self.effective_n_jobs(n_jobs)

    def effective_n_jobs(self, n_jobs: int) -> int:
        cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
        if n_jobs is None or n_jobs == -1:
            return cpus
        return max(1, min(n_jobs, cpus))

    def apply_async(self, func: Callable, callback=None):
        ref = _run_batch.remote(_ser.dumps_function(func))
        return _RtpuFuture(ref, callback)

    def abort_everything(self, ensure_ready: bool = True) -> None:
        pass  # in-flight tasks finish; their results are discarded

    def terminate(self) -> None:
        pass


class _RtpuFuture:
    """joblib waits via .get(timeout) on what apply_async returns."""

    def __init__(self, ref, callback):
        self._ref = ref
        if callback is not None:
            fut = ray_tpu._ctx.current_client.as_future(ref)
            fut.add_done_callback(lambda f: callback(None))

    def get(self, timeout=None) -> List[Any]:
        return ray_tpu.get(self._ref, timeout=timeout)
