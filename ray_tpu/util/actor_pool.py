"""ActorPool — load-balance tasks over a fixed set of actors.

Reference: ``python/ray/util/actor_pool.py`` (same public surface:
map/map_unordered/submit/get_next/get_next_unordered/has_next).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, TypeVar

from .. import get, wait

V = TypeVar("V")


class ActorPool:
    def __init__(self, actors: List[Any]):
        if not actors:
            raise ValueError("ActorPool needs at least one actor")
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0

    def map(self, fn: Callable[[Any, V], Any],
            values: Iterable[V]) -> Iterable[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, V], Any],
                      values: Iterable[V]) -> Iterable[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn: Callable[[Any, V], Any], value: V) -> None:
        """fn(actor, value) -> ObjectRef; blocks if no actor is idle."""
        if not self._idle:
            # wait for any in-flight call to finish, then reuse its actor
            ready, _ = wait(list(self._future_to_actor), num_returns=1)
            self._reclaim(ready[0])
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._future_to_actor[ref] = actor
        self._index_to_future[self._next_task_index] = ref
        self._next_task_index += 1

    def _reclaim(self, ref) -> None:
        actor = self._future_to_actor.pop(ref, None)
        if actor is not None:
            self._idle.append(actor)

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def get_next(self, timeout: float = None) -> Any:
        if not self.has_next():
            raise StopIteration("no pending results")
        idx = self._next_return_index
        ref = self._index_to_future[idx]
        if timeout is not None:
            # only consume the slot once the result is actually ready, so
            # a timeout leaves the pool state untouched and retryable
            ready, _ = wait([ref], num_returns=1, timeout=timeout)
            if not ready:
                raise TimeoutError(f"task {idx} not ready within {timeout}s")
        value = get(ref)
        del self._index_to_future[idx]
        self._next_return_index += 1
        self._reclaim(ref)
        return value

    def get_next_unordered(self, timeout: float = None) -> Any:
        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = wait(list(self._index_to_future.values()), num_returns=1,
                        timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        for idx, fut in list(self._index_to_future.items()):
            if fut == ref:
                del self._index_to_future[idx]
                break
        value = get(ref)
        self._reclaim(ref)
        return value

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor) -> None:
        self._idle.append(actor)
