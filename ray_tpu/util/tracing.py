"""Distributed tracing: spans with cross-process context propagation.

Reference analogue: ``python/ray/util/tracing/`` — OpenTelemetry spans
around task submission/execution with the trace context carried inside
the task spec. Same model here without the otel dependency (it is not a
baked-in package): W3C-style ids (128-bit trace, 64-bit span), a
thread-local context stack, automatic ``task::<name>`` spans around
remote execution, and export to the control plane where
``state.api.list_spans()`` / ``trace_timeline()`` read them back.

Enable with ``init(_system_config={"tracing_enabled": True})`` (or
``RTPU_TRACING_ENABLED=1``). Disabled, every hook is a no-op.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .._private import locksan
from .._private.config import CONFIG

_local = threading.local()
_buffer: List[dict] = []
_buffer_lock = locksan.lock("tracing.buffer")
_MAX_BUFFER = 10_000


def enabled() -> bool:
    return bool(CONFIG.tracing_enabled)


def _rand_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def current_span() -> Optional[dict]:
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None


def get_current_context() -> Optional[Dict[str, str]]:
    """Propagatable (trace_id, span_id) of the active span, or an
    inherited remote parent when no local span is open."""
    span = current_span()
    if span is not None:
        return {"trace_id": span["trace_id"], "span_id": span["span_id"]}
    return getattr(_local, "remote_parent", None)


def propagation_context() -> Optional[Dict[str, str]]:
    """What a submitter puts into the task spec. When tracing is on but
    no span is open, an EMPTY dict still rides along: it tells the
    executing node "trace this" even if that node's own config has
    tracing off (remote nodes don't see the driver's _system_config).
    An OPEN span propagates even when this process's config has tracing
    off — force-traced spans (serve request ingress, a spec that said
    "trace this") must not lose their trace at the next task boundary."""
    span = current_span()
    if span is not None:
        return {"trace_id": span["trace_id"], "span_id": span["span_id"]}
    if not enabled():
        return None
    return get_current_context() or {}


def set_remote_parent(ctx: Optional[Dict[str, str]]) -> None:
    """Adopt a caller's context (worker-side, before running a task)."""
    _local.remote_parent = ctx


def _new_span(name: str, parent: Optional[Dict[str, str]],
              attributes: Optional[Dict[str, Any]]) -> dict:
    return {
        "trace_id": (parent["trace_id"] if parent and "trace_id" in parent
                     else _rand_id(16)),
        "span_id": _rand_id(8),
        "parent_id": (parent["span_id"] if parent and "span_id" in parent
                      else None),
        "name": name,
        "start_time": time.time(),
        "end_time": None,
        "attributes": dict(attributes or {}),
        "status": "OK",
        "pid": os.getpid(),
    }


@contextlib.contextmanager
def start_span(name: str, attributes: Optional[Dict[str, Any]] = None,
               force: bool = False):
    """Open a span as a child of the current context. Yields the span
    dict (mutable: add attributes mid-flight). ``force`` traces even
    when local config has tracing off (used when the caller's spec says
    the submitting process is tracing)."""
    if not (enabled() or force):
        yield None
        return
    span = _new_span(name, get_current_context(), attributes)
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    stack.append(span)
    try:
        yield span
    except BaseException as e:
        span["status"] = f"ERROR:{type(e).__name__}"
        raise
    finally:
        span["end_time"] = time.time()
        stack.pop()
        _record(span)


def begin_span(name: str, parent: Optional[Dict[str, str]],
               attributes: Optional[Dict[str, Any]] = None) -> dict:
    """Stackless span for contexts where thread-local nesting is wrong
    (asyncio actors interleave many calls on one loop thread)."""
    return _new_span(name, parent, attributes)


def end_span(span: Optional[dict], error: Optional[str] = None) -> None:
    if span is None:
        return
    span["end_time"] = time.time()
    if error:
        span["status"] = f"ERROR:{error}"
    _record(span)


def _record(span: dict) -> None:
    with _buffer_lock:
        _buffer.append(span)
        if len(_buffer) > _MAX_BUFFER:
            del _buffer[:len(_buffer) - _MAX_BUFFER]


def drain() -> List[dict]:
    """Take all locally-buffered finished spans (flush transport)."""
    with _buffer_lock:
        out, _buffer[:] = list(_buffer), []
    return out


def flush() -> None:
    """Ship buffered spans to the control plane via the connected
    client (driver or worker). No-op when nothing is buffered. Not
    gated on ``enabled()``: a worker may hold force-traced spans while
    its own config has tracing off."""
    spans = drain()
    if not spans:
        return
    from .._private import context as _ctx
    client = _ctx.current_client
    if client is None:
        _local_requeue(spans)
        return
    try:
        client.send_profile_event("spans", spans)
    except Exception:          # noqa: BLE001 — tracing must never break work
        pass


_last_flush = 0.0


def maybe_flush(min_interval_s: float = 0.2) -> None:
    """Rate-limited flush for per-request call sites (the serve
    gateway): frequent enough that request lanes assemble promptly
    under traffic, bounded so a request storm doesn't pay one
    control-plane span frame each. Readers that need freshness
    (``state.list_spans`` / the timeline's request-lane builder) call
    ``flush()`` directly."""
    global _last_flush
    now = time.monotonic()
    if now - _last_flush >= min_interval_s:
        _last_flush = now
        flush()


def _local_requeue(spans: List[dict]) -> None:
    """Put drained-but-unshippable spans back at the buffer head. Clamp
    to _MAX_BUFFER afterwards (dropping the OLDEST overflow): repeated
    failed flushes must not grow the buffer without bound."""
    with _buffer_lock:
        _buffer[:0] = spans
        if len(_buffer) > _MAX_BUFFER:
            del _buffer[:len(_buffer) - _MAX_BUFFER]
