"""Scheduling strategies (reference: ``util/scheduling_strategies.py``).

The dataclasses live in ``_private.scheduler`` because the node-side
scheduler pattern-matches on them; this module is the public name.
"""

from .._private.scheduler import (  # noqa: F401
    DEFAULT,
    SPREAD,
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
