"""Distributed-safe tqdm-compatible progress bars.

Reference: ``python/ray/experimental/tqdm_ray.py`` — worker-side bars
emit magic JSON lines on stdout; the driver's log pump recognizes them
and renders a single in-place progress line instead of interleaving
raw prints from many processes. Same protocol shape here: the magic
token rides the existing worker-log channel, so no extra RPC surface.
"""

from __future__ import annotations

import json
import sys
import time
import uuid
from typing import Any, Iterable, Optional

MAGIC = "__rtpu_tqdm__:"

from .._private import locksan

_render_lock = locksan.lock("tqdm.render")
_last_render: dict = {}            # bar_id -> state (driver side)


def _emit(state: dict) -> None:
    """Worker side: ship the bar state as one magic stdout line (the
    log tailer forwards it; the driver renders)."""
    sys.stdout.write(MAGIC + json.dumps(state) + "\n")
    sys.stdout.flush()


def render_magic_line(line: str) -> bool:
    """Driver side: if ``line`` is a bar update, render it in place and
    return True (the log pump then suppresses the raw line)."""
    if not line.startswith(MAGIC):
        return False
    try:
        state = json.loads(line[len(MAGIC):])
    except ValueError:
        return False
    _render(state)
    return True


def _render(state: dict) -> None:
    with _render_lock:
        if state.get("closed"):
            _last_render.pop(state.get("id"), None)
            sys.stderr.write("\n")
            sys.stderr.flush()
            return
        _last_render[state.get("id")] = state
        n, total = state.get("n", 0), state.get("total")
        desc = state.get("desc") or "progress"
        if total:
            frac = n / max(total, 1)
            width = 24
            bar = "#" * int(frac * width)
            txt = (f"\r{desc}: {n}/{total} "
                   f"[{bar:<{width}}] {frac * 100:5.1f}%")
        else:
            txt = f"\r{desc}: {n}it"
        sys.stderr.write(txt)
        sys.stderr.flush()


class tqdm:
    """tqdm-compatible surface: iterate, update(), close(),
    set_description(); safe inside remote tasks/actors."""

    def __init__(self, iterable: Optional[Iterable] = None, *,
                 desc: str = "", total: Optional[int] = None,
                 **_ignored: Any):
        self._iterable = iterable
        self.desc = desc
        if total is None and iterable is not None:
            try:
                total = len(iterable)       # type: ignore[arg-type]
            except TypeError:
                total = None
        self.total = total
        self.n = 0
        self._id = uuid.uuid4().hex[:12]
        self._last_emit = 0.0
        self._closed = False
        self._report(force=True)

    # ------------------------------------------------------------- tqdm API
    def __iter__(self):
        if self._iterable is None:
            raise TypeError("this tqdm was created without an iterable")
        try:
            for item in self._iterable:
                yield item
                self.update(1)
        finally:
            self.close()

    def update(self, n: int = 1) -> None:
        self.n += n
        self._report()

    def set_description(self, desc: str) -> None:
        self.desc = desc
        self._report()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._report(force=True, closed=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ reporting
    def _report(self, force: bool = False, closed: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_emit < 0.1:
            return                      # rate-limit: 10 updates/s max
        self._last_emit = now
        state = {"id": self._id, "desc": self.desc, "n": self.n,
                 "total": self.total, "closed": closed}
        from .._private import context
        if context.in_worker:
            _emit(state)                # rendered on the driver
        else:
            _render(state)
