"""ray_tpu.util — user-facing utilities layered on the core API.

Mirrors the reference's ``python/ray/util/`` (placement groups,
scheduling strategies, actor pool, queue, collectives live in
``ray_tpu.comm``).
"""

from .placement_group import (  # noqa: F401
    PlacementGroup,
    placement_group,
    remove_placement_group,
)
from .scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
from .actor_pool import ActorPool  # noqa: F401
from .queue import Queue  # noqa: F401
