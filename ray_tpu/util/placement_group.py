"""Placement groups: gang reservation of resource bundles.

Reference: ``python/ray/util/placement_group.py:146`` (API) +
``gcs_placement_group_scheduler.h:274`` (2-phase reserve; ours is the
node-side ``reserve_bundle``/``release_bundle`` pair with rollback,
``_private/node.py``). On TPU the headline use is gang-scheduling one
worker per TPU host so a ``comm.device_mesh.MeshGroup`` can lay a
`jax.sharding.Mesh` over the gang (SURVEY §7.7c).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from .._private import context as _ctx
from .._private import protocol as P
from .._private.ids import PlacementGroupID
from .._private.scheduler import PlacementGroupSchedulingStrategy  # noqa: F401

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    """Handle to a (possibly not-yet-reserved) placement group."""

    def __init__(self, pg_id: PlacementGroupID,
                 bundles: List[Dict[str, float]], strategy: str,
                 name: str = "", assignment: Optional[list] = None):
        self.id = pg_id
        self._bundles = bundles
        self._strategy = strategy
        self._name = name
        self._assignment = assignment

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self._bundles)

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    @property
    def strategy(self) -> str:
        return self._strategy

    def is_ready(self) -> bool:
        return self._assignment is not None

    def ready(self, timeout: Optional[float] = None) -> "PlacementGroup":
        """Block until the reservation succeeds (retrying as resources
        free up — the reference keeps pending PGs queued in the GCS)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.02
        while self._assignment is None:
            self._try_create()
            if self._assignment is not None:
                break
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"placement group {self.id} not ready within {timeout}s")
            time.sleep(delay)
            delay = min(delay * 2, 0.5)
        return self

    def _try_create(self) -> None:
        client = _ctx.require_client()
        spec = P.PlacementGroupSpec(pg_id=self.id, bundles=self._bundles,
                                    strategy=self._strategy, name=self._name)
        assignment = client.create_placement_group(spec)
        if assignment is not None:
            self._assignment = assignment

    def __reduce__(self):
        return (_rebuild_pg, (self.id.binary(), self._bundles,
                              self._strategy, self._name, self._assignment))


def _rebuild_pg(id_bytes, bundles, strategy, name, assignment):
    return PlacementGroup(PlacementGroupID(id_bytes), bundles, strategy,
                          name, assignment)


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "",
                    lifetime: Optional[str] = None) -> PlacementGroup:
    """Reserve resource bundles across the cluster (async: call
    ``.ready()`` to block on reservation; the first attempt is made
    eagerly)."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or not all(isinstance(b, dict) and b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty "
                         "resource dicts")
    del lifetime  # detached PGs: accepted for parity, all PGs job-scoped
    pg = PlacementGroup(PlacementGroupID.from_random(), list(bundles), strategy,
                        name)
    pg._try_create()
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    """Release the reservation and its bundles."""
    _ctx.require_client().remove_placement_group(pg.id)
    pg._assignment = None
