"""Distributed FIFO queue backed by an actor.

Reference: ``python/ray/util/queue.py`` (Queue with put/get/
put_nowait/get_nowait/qsize/empty/full, Empty/Full exceptions).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

from ..api import remote


class Empty(Exception):
    pass


class Full(Exception):
    pass


@remote(num_cpus=0)
class _QueueActor:
    def __init__(self, maxsize: int):
        from collections import deque
        self._maxsize = maxsize
        self._q = deque()

    def qsize(self) -> int:
        return len(self._q)

    def put(self, item) -> bool:
        if self._maxsize > 0 and len(self._q) >= self._maxsize:
            return False
        self._q.append(item)
        return True

    def get(self):
        if not self._q:
            return False, None
        return True, self._q.popleft()

    def put_batch(self, items) -> bool:
        if self._maxsize > 0 and len(self._q) + len(items) > self._maxsize:
            return False
        self._q.extend(items)
        return True

    def get_batch(self, n: int):
        """All-or-nothing: never dequeues unless n items are available."""
        if len(self._q) < n:
            return None
        return [self._q.popleft() for _ in range(n)]


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = actor_options or {}
        self.maxsize = maxsize
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    def __reduce__(self):
        return (_rebuild_queue, (self.maxsize, self.actor))

    def qsize(self) -> int:
        from .. import get
        return get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        from .. import get
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if get(self.actor.put.remote(item)):
                return
            if not block:
                raise Full
            if deadline is not None and time.monotonic() >= deadline:
                raise Full
            time.sleep(0.01)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        from .. import get as rget
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = rget(self.actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty
            time.sleep(0.01)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        from .. import get
        if not get(self.actor.put_batch.remote(list(items))):
            raise Full

    def get_nowait_batch(self, n: int) -> List[Any]:
        from .. import get
        items = get(self.actor.get_batch.remote(n))
        if items is None:
            raise Empty(f"queue has fewer than {n} items")
        return items

    def shutdown(self) -> None:
        from .. import kill
        kill(self.actor)


def _rebuild_queue(maxsize, actor):
    q = Queue.__new__(Queue)
    q.maxsize = maxsize
    q.actor = actor
    return q
