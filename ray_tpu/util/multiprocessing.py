"""``multiprocessing.Pool`` drop-in backed by cluster tasks.

Reference analogue: ``python/ray/util/multiprocessing/pool.py`` — the
same surface (``map``/``starmap``/``imap``/``imap_unordered``/
``apply``/``apply_async``/context manager) so existing Pool code runs
on the cluster by changing one import. Work is submitted as chunked
remote tasks; results stream back through the object store.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu
from .._private import serialization as _ser


@ray_tpu.remote
def _run_chunk(fn_blob: bytes, chunk: list, star: bool) -> list:
    fn = _ser.loads_function(fn_blob)
    if star:
        return [fn(*args) for args in chunk]
    return [fn(x) for x in chunk]


@ray_tpu.remote
def _run_call(fn_blob: bytes):
    return _ser.loads_function(fn_blob)()


class AsyncResult:
    """Matches ``multiprocessing.pool.AsyncResult``."""

    def __init__(self, refs: List, chunked: bool, callback=None,
                 error_callback=None, single: bool = False):
        self._refs = refs
        self._chunked = chunked
        self._single = single
        self._result: Optional[list] = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        t = threading.Thread(target=self._collect,
                             args=(callback, error_callback), daemon=True)
        t.start()

    def _collect(self, callback, error_callback) -> None:
        try:
            chunks = ray_tpu.get(self._refs)
            if self._chunked:
                self._result = list(itertools.chain.from_iterable(chunks))
            elif self._single:
                self._result = chunks[0]    # apply(): one scalar result
            else:
                self._result = chunks
            if callback is not None:
                callback(self._result)
        except BaseException as e:  # noqa: BLE001 — delivered via get()
            self._error = e
            if error_callback is not None:
                error_callback(e)
        finally:
            self._done.set()

    def wait(self, timeout: Optional[float] = None) -> None:
        self._done.wait(timeout)

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        return self._error is None

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("result not ready within timeout")
        if self._error is not None:
            raise self._error
        return self._result


class Pool:
    """Task-backed process pool (reference: ``ray.util.multiprocessing``).

    ``processes`` bounds in-flight chunks, not real processes — workers
    come from the node's shared pool.
    """

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._processes = processes or int(
            ray_tpu.cluster_resources().get("CPU", 4))
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False

    # -- helpers -------------------------------------------------------
    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)], chunksize

    def _wrap(self, fn):
        if self._initializer is None:
            return fn
        initializer, initargs = self._initializer, self._initargs
        # worker-local one-time init, keyed per process
        def wrapped(*a, **kw):
            import os
            flag = f"_rtpu_pool_init_{os.getpid()}"
            import builtins
            if not getattr(builtins, flag, False):
                initializer(*initargs)
                setattr(builtins, flag, True)
            return fn(*a, **kw)
        return wrapped

    def _check_open(self) -> None:
        if self._closed:
            raise ValueError("Pool not running")

    def _submit_chunks(self, fn, chunks: list, star: bool) -> list:
        # cloudpickle by value: a user callable from the driver's script
        # or test module is not importable inside workers
        blob = _ser.dumps_function(self._wrap(fn))
        return [_run_chunk.remote(blob, chunk, star) for chunk in chunks]

    # -- the multiprocessing.Pool surface ------------------------------
    def apply(self, fn, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args: tuple = (), kwds: Optional[dict] = None,
                    callback=None, error_callback=None) -> AsyncResult:
        self._check_open()
        kwds = kwds or {}
        wrapped = self._wrap(fn)
        blob = _ser.dumps_function(lambda: wrapped(*args, **kwds))
        ref = _run_call.remote(blob)
        return AsyncResult([ref], chunked=False, single=True,
                           callback=callback,
                           error_callback=error_callback)

    def map(self, fn, iterable: Iterable,
            chunksize: Optional[int] = None) -> list:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable: Iterable,
                  chunksize: Optional[int] = None, callback=None,
                  error_callback=None) -> AsyncResult:
        self._check_open()
        chunks, _ = self._chunks(iterable, chunksize)
        refs = self._submit_chunks(fn, chunks, star=False)
        return AsyncResult(refs, chunked=True, callback=callback,
                           error_callback=error_callback)

    def starmap(self, fn, iterable: Iterable,
                chunksize: Optional[int] = None) -> list:
        self._check_open()
        chunks, _ = self._chunks(iterable, chunksize)
        refs = self._submit_chunks(fn, chunks, star=True)
        return AsyncResult(refs, chunked=True).get()

    def starmap_async(self, fn, iterable: Iterable,
                      chunksize: Optional[int] = None, callback=None,
                      error_callback=None) -> AsyncResult:
        self._check_open()
        chunks, _ = self._chunks(iterable, chunksize)
        refs = self._submit_chunks(fn, chunks, star=True)
        return AsyncResult(refs, chunked=True, callback=callback,
                          error_callback=error_callback)

    def imap(self, fn, iterable: Iterable, chunksize: Optional[int] = None):
        """Ordered streaming results."""
        self._check_open()
        chunks, _ = self._chunks(iterable, chunksize)
        refs = self._submit_chunks(fn, chunks, star=False)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn, iterable: Iterable,
                       chunksize: Optional[int] = None):
        """Results in completion order (chunk granularity)."""
        self._check_open()
        chunks, _ = self._chunks(iterable, chunksize)
        refs = self._submit_chunks(fn, chunks, star=False)
        pending = list(refs)
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(done[0])

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
