"""User-facing metrics API: Counter / Gauge / Histogram.

Reference: ``python/ray/util/metrics.py`` (same three types, tag
support) + the per-node ``MetricsAgent`` → Prometheus pipeline
(``_private/metrics_agent.py:416``). The transport underneath is
``_private/telemetry.py``: every record call is a process-local
sharded-dict update (no RPC on the sample path); a background flusher
batch-pushes deltas to the control plane, where runtime and user
metrics merge into one cluster-wide table. Export is Prometheus text
format via ``export_prometheus()`` / ``start_metrics_http()``, the
dashboard's ``/api/metrics`` JSON endpoint, and
``state.api.summarize_metrics()``.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence

from .._private import telemetry

_DEFAULT_BUCKETS = telemetry.DEFAULT_BUCKETS


class _Metric:
    KIND = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        # Histogram sets its buckets before delegating here; one define
        # covers all kinds so bucket/kind conflicts are caught centrally
        self._buckets = getattr(self, "_buckets", None)
        telemetry.define(self.KIND, name, description, self._buckets)

    def set_default_tags(self, tags: Dict[str, str]) -> "_Metric":
        self._default_tags = dict(tags)
        return self

    def _tags_tuple(self, tags: Optional[Dict[str, str]]) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))


class Counter(_Metric):
    KIND = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        telemetry.counter_inc(self._name, float(value),
                              self._tags_tuple(tags))


class Gauge(_Metric):
    KIND = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        telemetry.gauge_set(self._name, float(value),
                            self._tags_tuple(tags))


class Histogram(_Metric):
    KIND = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = _DEFAULT_BUCKETS,
                 tag_keys: Sequence[str] = ()):
        self._buckets = tuple(boundaries)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        telemetry.hist_observe(self._name, float(value),
                               self._tags_tuple(tags), self._buckets)


# ------------------------------------------------------------- exposition

def _fmt_tags(tags: tuple) -> str:
    if not tags:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in tags)
    return "{" + inner + "}"


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def format_prometheus(snap: dict, include_exemplars: bool = True) -> str:
    """Prometheus text exposition of one metrics snapshot (the merged
    control-plane table or a process-local one). ``# HELP``/``# TYPE``
    are emitted once per metric NAME (exposition-format requirement),
    with every tagged series grouped under its header. Histogram
    exemplars (trace ids captured while tracing was enabled) ride the
    matching bucket line in OpenMetrics syntax — pass
    ``include_exemplars=False`` for surfaces that advertise the classic
    ``text/plain; version=0.0.4`` content type, whose parsers reject
    the exemplar token (the HTTP scrape endpoints do)."""
    meta = snap.get("meta") or {}
    by_name: Dict[str, dict] = {}

    def series_of(name: str) -> dict:
        ent = by_name.get(name)
        if ent is None:
            ent = by_name[name] = {"counters": [], "gauges": [],
                                   "hists": [], "digests": []}
        return ent

    for (name, tags), value in (snap.get("counters") or {}).items():
        series_of(name)["counters"].append((tags, value))
    for (name, tags), (value, _ts) in (snap.get("gauges") or {}).items():
        series_of(name)["gauges"].append((tags, value))
    for (name, tags), h in (snap.get("hists") or {}).items():
        series_of(name)["hists"].append((tags, h))
    for (name, tags), d in (snap.get("digests") or {}).items():
        series_of(name)["digests"].append((tags, d))
    if snap.get("dropped_series"):
        series_of("rtpu_telemetry_dropped_series_total")["counters"].append(
            ((), float(snap["dropped_series"])))
        meta = {**meta, "rtpu_telemetry_dropped_series_total": {
            "kind": "counter",
            "description": "Metric series dropped by the control plane "
                           "(cardinality cap or bucket conflicts)"}}

    lines: List[str] = []
    for name in sorted(by_name):
        ent = by_name[name]
        m = meta.get(name) or {}
        kind = m.get("kind") or ("histogram" if ent["hists"] else
                                 "summary" if ent["digests"] else
                                 "gauge" if ent["gauges"] else "counter")
        # quantile digests export as the Prometheus summary type
        # (quantile-labelled gauge lines + _sum/_count)
        if kind == "digest":
            kind = "summary"
        desc = m.get("description") or ""
        if desc:
            lines.append(f"# HELP {name} {_escape_help(desc)}")
        lines.append(f"# TYPE {name} {kind}")
        for tags, value in sorted(ent["counters"]) + sorted(ent["gauges"]):
            lines.append(f"{name}{_fmt_tags(tags)} {float(value)}")
        for tags, h in sorted(ent["hists"], key=lambda kv: kv[0]):
            buckets = tuple(h.get("buckets") or _DEFAULT_BUCKETS)
            counts = list(h.get("counts") or [0] * (len(buckets) + 1))
            ex = h.get("exemplar") if include_exemplars else None
            ex_idx = (min(bisect_left(buckets, ex["value"]), len(buckets))
                      if ex else -1)
            cumulative = 0
            for i, b in enumerate(buckets):
                cumulative += counts[i] if i < len(counts) else 0
                line = (f"{name}_bucket"
                        f"{_fmt_tags(tags + (('le', str(b)),))} "
                        f"{cumulative}")
                if i == ex_idx:
                    line += (f' # {{trace_id="{ex["trace_id"]}"}} '
                             f'{ex["value"]} {ex["ts"]}')
                lines.append(line)
            total = int(h.get("count", sum(counts)))
            inf_line = (f"{name}_bucket"
                        f"{_fmt_tags(tags + (('le', '+Inf'),))} {total}")
            if ex_idx == len(buckets):
                inf_line += (f' # {{trace_id="{ex["trace_id"]}"}} '
                             f'{ex["value"]} {ex["ts"]}')
            lines.append(inf_line)
            lines.append(f"{name}_sum{_fmt_tags(tags)} "
                         f"{float(h.get('sum', 0.0))}")
            lines.append(f"{name}_count{_fmt_tags(tags)} {total}")
        for tags, d in sorted(ent["digests"], key=lambda kv: kv[0]):
            for q in (0.5, 0.9, 0.95, 0.99):
                lines.append(
                    f"{name}{_fmt_tags(tags + (('quantile', str(q)),))} "
                    f"{telemetry.digest_quantile(d, q)}")
            lines.append(f"{name}_sum{_fmt_tags(tags)} "
                         f"{float(d.get('sum', 0.0))}")
            lines.append(f"{name}_count{_fmt_tags(tags)} "
                         f"{int(d.get('count', 0))}")
    return "\n".join(lines) + ("\n" if lines else "")


def metrics_snapshot() -> dict:
    """The merged cluster-wide metrics table (this process's shards are
    flushed first). Falls back to the process-local view when no
    runtime is connected."""
    telemetry.flush()
    from .._private import context as _ctx
    client = _ctx.current_client
    if client is not None:
        try:
            snap = client.state_query("metrics", None)
            if snap is not None:
                return snap
        except Exception:   # noqa: BLE001 — export must not raise
            pass
    return telemetry.snapshot_local()


def export_prometheus() -> str:
    """Prometheus text exposition of all recorded metrics (head scrape
    surface; reference: the per-node agent's scrape endpoint)."""
    return format_prometheus(metrics_snapshot())


_http_server = None


def start_metrics_http(host: str = "127.0.0.1", port: int = 0) -> str:
    """Serve GET /metrics in Prometheus format (reference: the per-node
    agent's scrape endpoint)."""
    global _http_server
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            # classic 0.0.4 content type: no exemplar tokens
            body = format_prometheus(metrics_snapshot(),
                                     include_exemplars=False).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    _http_server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=_http_server.serve_forever,
                     daemon=True).start()
    return f"http://{host}:{_http_server.server_address[1]}/metrics"
