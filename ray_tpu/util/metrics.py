"""User-facing metrics API: Counter / Gauge / Histogram.

Reference: ``python/ray/util/metrics.py`` (same three types, tag
support) + the per-node ``MetricsAgent`` → Prometheus pipeline
(``_private/metrics_agent.py:416``). Here every process records locally
and pushes to a named aggregator actor (fire-and-forget); export is
Prometheus text format via ``export_prometheus()`` or an HTTP endpoint
(``start_metrics_http``).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import remote

_AGGREGATOR_NAME = "rtpu:metrics_aggregator"
_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                    5.0, 10.0)


@remote(num_cpus=0, max_concurrency=8)
class _Aggregator:
    def __init__(self):
        self._counters: Dict[tuple, float] = defaultdict(float)
        self._gauges: Dict[tuple, float] = {}
        self._hists: Dict[tuple, List[float]] = defaultdict(list)
        self._meta: Dict[str, dict] = {}

    def record(self, kind: str, name: str, description: str,
               tags: tuple, value: float, buckets=None) -> None:
        key = (name, tags)
        self._meta[name] = {"kind": kind, "description": description,
                            "buckets": buckets}
        if kind == "counter":
            self._counters[key] += value
        elif kind == "gauge":
            self._gauges[key] = value
        else:
            self._hists[key].append(value)

    def snapshot(self) -> dict:
        return {
            "counters": dict(self._counters),
            "gauges": dict(self._gauges),
            "histograms": {k: list(v) for k, v in self._hists.items()},
            "meta": dict(self._meta),
        }


_agg_cache = None          # (client, actor) — invalidated on re-init
_agg_lock = threading.Lock()


def _get_aggregator(create: bool = True):
    """Named-actor rendezvous. Creation can race across workers — the
    loser's creation fails (duplicate name), so confirm with a real call
    and fall back to lookup."""
    global _agg_cache
    from .. import get, get_actor
    from .._private import context as _ctx
    client = _ctx.require_client()
    with _agg_lock:
        if _agg_cache is not None and _agg_cache[0] is client:
            return _agg_cache[1]
        _agg_cache = None
        try:
            actor = get_actor(_AGGREGATOR_NAME)
            _agg_cache = (client, actor)
            return actor
        except ValueError:
            if not create:
                return None
        try:
            actor = _Aggregator.options(name=_AGGREGATOR_NAME,
                                        lifetime="detached").remote()
            get(actor.snapshot.remote())    # forces creation to resolve
            _agg_cache = (client, actor)
            return actor
        except Exception:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                try:
                    actor = get_actor(_AGGREGATOR_NAME)
                    _agg_cache = (client, actor)
                    return actor
                except ValueError:
                    time.sleep(0.05)
            raise


class _Metric:
    KIND = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._buckets = None

    def set_default_tags(self, tags: Dict[str, str]) -> "_Metric":
        self._default_tags = dict(tags)
        return self

    def _tags_tuple(self, tags: Optional[Dict[str, str]]) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))

    def _record(self, value: float, tags: Optional[Dict[str, str]]):
        agg = _get_aggregator()
        agg.record.remote(self.KIND, self._name, self._description,
                          self._tags_tuple(tags), float(value),
                          self._buckets)


class Counter(_Metric):
    KIND = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        self._record(value, tags)


class Gauge(_Metric):
    KIND = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        self._record(value, tags)


class Histogram(_Metric):
    KIND = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = _DEFAULT_BUCKETS,
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self._buckets = tuple(boundaries)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        self._record(value, tags)


def export_prometheus() -> str:
    """Prometheus text exposition of all recorded metrics."""
    from .. import get
    agg = _get_aggregator(create=False)
    if agg is None:
        return ""
    snap = get(agg.snapshot.remote())
    lines: List[str] = []

    def fmt_tags(tags: tuple) -> str:
        if not tags:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in tags)
        return "{" + inner + "}"

    meta = snap["meta"]
    for (name, tags), value in sorted(snap["counters"].items()):
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name}{fmt_tags(tags)} {value}")
    for (name, tags), value in sorted(snap["gauges"].items()):
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{fmt_tags(tags)} {value}")
    for (name, tags), values in sorted(snap["histograms"].items()):
        buckets = (meta.get(name, {}).get("buckets")
                   or _DEFAULT_BUCKETS)
        lines.append(f"# TYPE {name} histogram")
        cumulative = 0
        for b in buckets:
            cumulative = sum(1 for v in values if v <= b)
            tag_str = fmt_tags(tags + (("le", str(b)),))
            lines.append(f"{name}_bucket{tag_str} {cumulative}")
        inf_tags = fmt_tags(tags + (("le", "+Inf"),))
        lines.append(f"{name}_bucket{inf_tags} {len(values)}")
        lines.append(f"{name}_sum{fmt_tags(tags)} {sum(values)}")
        lines.append(f"{name}_count{fmt_tags(tags)} {len(values)}")
    return "\n".join(lines) + ("\n" if lines else "")


_http_server = None


def start_metrics_http(host: str = "127.0.0.1", port: int = 0) -> str:
    """Serve GET /metrics in Prometheus format (reference: the per-node
    agent's scrape endpoint)."""
    global _http_server
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            body = export_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    _http_server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=_http_server.serve_forever,
                     daemon=True).start()
    return f"http://{host}:{_http_server.server_address[1]}/metrics"
