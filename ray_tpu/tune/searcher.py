"""Pluggable search algorithms.

Reference: ``python/ray/tune/search/searcher.py`` (Searcher base:
suggest / on_trial_result / on_trial_complete / save / restore),
``tune/search/concurrency_limiter.py`` and the suggestion-based
adapters (``tune/search/optuna``). The TPE searcher is an original
lite implementation of tree-structured Parzen estimation over this
module's Domain types — good/bad split + per-dimension kernel density
ratio — not a port of hyperopt.
"""

from __future__ import annotations

import math
import pickle
import random
from typing import Any, Dict, List, Optional

from .search import (BasicVariantGenerator, Categorical, Domain,
                     LogUniform, RandInt, Uniform, _set_path, _split_spec)

# suggest() sentinel: the searcher will never produce another config
FINISHED = "FINISHED"


class Searcher:
    """Base class for search algorithms.

    Lifecycle: the Tuner calls ``set_search_properties`` once, then
    ``suggest(trial_id)`` per new trial (``None`` = nothing right now,
    ``FINISHED`` = exhausted), ``on_trial_result`` per report, and
    ``on_trial_complete`` exactly once per trial.
    """

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str],
                              param_space: Dict[str, Any]) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass

    # -- persistence (experiment resume) --------------------------------
    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump(self.__dict__, f)

    def restore(self, path: str) -> None:
        with open(path, "rb") as f:
            self.__dict__.update(pickle.load(f))


class BasicVariantSearcher(Searcher):
    """The default grid x random generator on the Searcher interface."""

    def __init__(self, param_space: Optional[Dict[str, Any]] = None,
                 num_samples: int = 1, seed: int = 0, **kw):
        super().__init__(**kw)
        self._param_space = param_space
        self._num_samples = num_samples
        self._seed = seed
        self._it = None

    def set_search_properties(self, metric, mode, param_space) -> bool:
        super().set_search_properties(metric, mode, param_space)
        if self._param_space is None:
            self._param_space = param_space
        return True

    def suggest(self, trial_id: str):
        if self._it is None:
            self._it = BasicVariantGenerator(
                self._param_space or {}, self._num_samples,
                seed=self._seed).variants()
        try:
            return next(self._it)
        except StopIteration:
            return FINISHED

    def save(self, path: str) -> None:  # iterator isn't picklable
        state = {k: v for k, v in self.__dict__.items() if k != "_it"}
        with open(path, "wb") as f:
            pickle.dump(state, f)


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions from the wrapped searcher
    (reference: ``tune/search/concurrency_limiter.py``)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        if max_concurrent < 1:
            raise ValueError("max_concurrent must be >= 1")
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def set_search_properties(self, metric, mode, param_space) -> bool:
        super().set_search_properties(metric, mode, param_space)
        return self.searcher.set_search_properties(metric, mode,
                                                   param_space)

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg is not FINISHED:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result) -> None:
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None,
                          error: bool = False) -> None:
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)

    def save(self, path: str) -> None:
        self.searcher.save(path)

    def restore(self, path: str) -> None:
        self.searcher.restore(path)


class TPESearcher(Searcher):
    """Tree-structured Parzen estimator, lite.

    Completed trials are split into good (best ``gamma`` quantile) and
    bad sets; each continuous dimension gets a 1-D Parzen (Gaussian
    kernel) density per set, and ``n_candidates`` draws from the good
    density are scored by l(x)/g(x) — highest ratio wins. Categorical
    dimensions use count-weighted draws with a uniform prior. Falls
    back to random sampling for the first ``n_initial`` trials.
    """

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None, *, n_initial: int = 8,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed: int = 0):
        super().__init__(metric, mode)
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._space: List = []          # [(path, Domain)]
        self._param_space: Dict[str, Any] = {}
        self._suggested: Dict[str, Dict[str, Any]] = {}
        self._obs: List = []            # [(flat_values, score)]

    def set_search_properties(self, metric, mode, param_space) -> bool:
        super().set_search_properties(metric, mode, param_space)
        self._param_space = param_space or {}
        self._space = [(p, d) for p, d in _split_spec(self._param_space)
                       if isinstance(d, Domain)]
        return True

    # -- domain helpers --------------------------------------------------
    @staticmethod
    def _to_unit(dom: Domain, v):
        """Map a value into the dimension's working space (log for
        LogUniform) or None for categoricals."""
        if isinstance(dom, LogUniform):
            return math.log(v)
        if isinstance(dom, (Uniform, RandInt)):
            return float(v)
        return None

    @staticmethod
    def _from_unit(dom: Domain, x):
        if isinstance(dom, LogUniform):
            return math.exp(x)
        if isinstance(dom, RandInt):
            v = int(round(x))
            v = max(dom.low, min(dom.high - 1, v))
            if dom.q:
                v = int(round(v / dom.q) * dom.q)
            return v
        if isinstance(dom, Uniform):
            v = max(dom.low, min(dom.high, x))
            if dom.q:
                v = round(v / dom.q) * dom.q
            return v
        return x

    def _bounds(self, dom: Domain):
        if isinstance(dom, LogUniform):
            return math.log(dom.low), math.log(dom.high)
        return float(dom.low), float(dom.high)

    def _sample_parzen(self, xs: List[float], lo: float, hi: float):
        """Draw one point from a Parzen mixture over xs."""
        if not xs:
            return self._rng.uniform(lo, hi)
        sigma = max((hi - lo) / max(len(xs), 1), 1e-12)
        mu = self._rng.choice(xs)
        return min(hi, max(lo, self._rng.gauss(mu, sigma)))

    @staticmethod
    def _parzen_pdf(x: float, xs: List[float], lo: float, hi: float):
        if not xs:
            return 1.0 / max(hi - lo, 1e-12)
        sigma = max((hi - lo) / max(len(xs), 1), 1e-12)
        acc = 0.0
        for mu in xs:
            z = (x - mu) / sigma
            acc += math.exp(-0.5 * z * z) / sigma
        return acc / len(xs) + 1e-12

    # -- searcher interface ----------------------------------------------
    def suggest(self, trial_id: str):
        if not self._space:
            return {}          # nothing to search; Tuner caps count
        flat: Dict[int, Any] = {}
        if len(self._obs) < self.n_initial:
            for i, (_, dom) in enumerate(self._space):
                flat[i] = dom.sample(self._rng)
        else:
            scored = sorted(self._obs, key=lambda o: o[1])
            n_good = max(1, int(math.ceil(self.gamma * len(scored))))
            good, bad = scored[:n_good], scored[n_good:]
            for i, (_, dom) in enumerate(self._space):
                if isinstance(dom, Categorical):
                    counts = {c: 1.0 for c in dom.categories}  # prior
                    for values, _ in good:
                        if values[i] in counts:
                            counts[values[i]] += 1.0
                    cats = list(counts)
                    flat[i] = self._rng.choices(
                        cats, weights=[counts[c] for c in cats])[0]
                    continue
                lo, hi = self._bounds(dom)
                gx = [self._to_unit(dom, v[i]) for v, _ in good]
                bx = [self._to_unit(dom, v[i]) for v, _ in bad]
                best_x, best_ratio = None, -1.0
                for _ in range(self.n_candidates):
                    x = self._sample_parzen(gx, lo, hi)
                    ratio = (self._parzen_pdf(x, gx, lo, hi)
                             / self._parzen_pdf(x, bx, lo, hi))
                    if ratio > best_ratio:
                        best_x, best_ratio = x, ratio
                flat[i] = self._from_unit(dom, best_x)
        config: Dict[str, Any] = {}
        for i, (path, _) in enumerate(self._space):
            _set_path(config, path, flat[i])
        self._suggested[trial_id] = {
            i: flat[i] for i in range(len(self._space))}
        return config

    def on_trial_complete(self, trial_id, result=None,
                          error: bool = False) -> None:
        values = self._suggested.pop(trial_id, None)
        if values is None or error or not result:
            return
        score = result.get(self.metric)
        if score is None:
            return
        score = float(score)
        if (self.mode or "min") == "max":
            score = -score
        self._obs.append((values, score))

    def save(self, path: str) -> None:
        state = dict(self.__dict__)
        state["_rng"] = self._rng.getstate()
        with open(path, "wb") as f:
            pickle.dump(state, f)

    def restore(self, path: str) -> None:
        with open(path, "rb") as f:
            state = pickle.load(f)
        rng_state = state.pop("_rng")
        self.__dict__.update(state)
        self._rng = random.Random()
        self._rng.setstate(rng_state)


class OptunaSearcher(Searcher):
    """Adapter for an installed optuna (reference:
    ``tune/search/optuna/optuna_search.py``). Gated: optuna is an
    optional dependency and absent from the target image."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None, *, seed: int = 0,
                 sampler: Any = None):
        try:
            import optuna  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "OptunaSearcher requires the optional 'optuna' package, "
                "which is not installed. Use TPESearcher for a "
                "dependency-free suggestion searcher.") from e
        import optuna
        super().__init__(metric, mode)
        self._seed = seed
        direction = "maximize" if (mode or "min") == "max" else "minimize"
        self._study = optuna.create_study(
            direction=direction,
            sampler=sampler or optuna.samplers.TPESampler(seed=seed))
        self._space: List = []
        self._trials: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, param_space) -> bool:
        super().set_search_properties(metric, mode, param_space)
        self._space = [(p, d) for p, d in _split_spec(param_space or {})
                       if isinstance(d, Domain)]
        return True

    def suggest(self, trial_id: str):
        ot = self._study.ask()
        config: Dict[str, Any] = {}
        for path, dom in self._space:
            name = ".".join(path)
            if isinstance(dom, Categorical):
                v = ot.suggest_categorical(name, dom.categories)
            elif isinstance(dom, LogUniform):
                v = ot.suggest_float(name, dom.low, dom.high, log=True)
            elif isinstance(dom, RandInt):
                v = ot.suggest_int(name, dom.low, dom.high - 1)
            elif isinstance(dom, Uniform):
                v = ot.suggest_float(name, dom.low, dom.high)
            else:
                v = dom.sample(random.Random(self._seed))
            _set_path(config, path, v)
        self._trials[trial_id] = ot
        return config

    def on_trial_complete(self, trial_id, result=None,
                          error: bool = False) -> None:
        ot = self._trials.pop(trial_id, None)
        if ot is None:
            return
        import optuna
        if error or not result or result.get(self.metric) is None:
            self._study.tell(ot,
                             state=optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(ot, float(result[self.metric]))
