"""Search spaces + variant generation.

Reference: ``tune/search/sample.py`` (Domain/Categorical/Float/Integer,
grid_search) and ``tune/search/basic_variant.py`` (BasicVariantGenerator
— cartesian grid expansion x num_samples random draws).
"""

from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, Iterator, List


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, low, high, q=None):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        if self.q:
            v = round(v / self.q) * self.q
        return v


class LogUniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low),
                                    math.log(self.high)))


class RandInt(Domain):
    def __init__(self, low, high, q=None):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        v = rng.randrange(self.low, self.high)
        if self.q:
            v = int(round(v / self.q) * self.q)
        return v


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


class _GridSearch:
    def __init__(self, values):
        self.values = list(values)


# -- public constructors (reference: ``tune/search/sample.py``) -----------

def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def quniform(low, high, q) -> Uniform:
    return Uniform(low, high, q)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def qrandint(low, high, q) -> RandInt:
    return RandInt(low, high, q)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values) -> Dict[str, Any]:
    return {"grid_search": list(values)}


# -- variant generation ----------------------------------------------------

def _split_spec(spec: Dict[str, Any], path=()):
    """Walk a (possibly nested) param space, yielding (path, domain)."""
    for key, value in spec.items():
        p = path + (key,)
        if isinstance(value, dict) and "grid_search" in value \
                and len(value) == 1:
            yield p, _GridSearch(value["grid_search"])
        elif isinstance(value, dict):
            yield from _split_spec(value, p)
        elif isinstance(value, Domain):
            yield p, value


def _set_path(config: Dict[str, Any], path, value) -> None:
    d = config
    for key in path[:-1]:
        d = d.setdefault(key, {})
    d[path[-1]] = value


def _deep_copy_static(spec):
    if isinstance(spec, dict):
        if "grid_search" in spec and len(spec) == 1:
            return None
        return {k: _deep_copy_static(v) for k, v in spec.items()}
    if isinstance(spec, Domain):
        return None
    return spec


class BasicVariantGenerator:
    """Grid cartesian product x num_samples random draws."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: int = 0):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self) -> Iterator[Dict[str, Any]]:
        entries = list(_split_spec(self.param_space))
        grids = [(p, d) for p, d in entries if isinstance(d, _GridSearch)]
        domains = [(p, d) for p, d in entries
                   if not isinstance(d, _GridSearch)]

        def grid_combos(i, acc):
            if i == len(grids):
                yield list(acc)
                return
            path, g = grids[i]
            for v in g.values:
                acc.append((path, v))
                yield from grid_combos(i + 1, acc)
                acc.pop()

        for _ in range(self.num_samples):
            for combo in grid_combos(0, []):
                config = _deep_copy_static(self.param_space) or {}
                for path, v in combo:
                    _set_path(config, path, v)
                for path, d in domains:
                    _set_path(config, path, d.sample(self.rng))
                yield config
