"""Trial schedulers: FIFO, ASHA, PBT.

Reference: ``tune/schedulers/async_hyperband.py`` (AsyncHyperBand/ASHA),
``tune/schedulers/pbt.py`` (PopulationBasedTraining),
``tune/schedulers/trial_scheduler.py`` (decision protocol CONTINUE/STOP).
Decisions are made on every reported result.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
# PBT: restart the trial from a better trial's checkpoint w/ mutated config
EXPLOIT = "EXPLOIT"


class FIFOScheduler:
    def on_result(self, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial) -> None:
        pass


class ASHAScheduler:
    """Asynchronous Successive Halving.

    Rungs at max_t / reduction_factor^k. When a trial reaches a rung, it
    continues only if its metric is in the top 1/reduction_factor of
    completed entries at that rung (async: decided against results so
    far, no waiting for a full bracket).
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3.0,
                 time_attr: str = "training_iteration"):
        assert mode in ("min", "max")
        self.metric, self.mode = metric, mode
        self.max_t, self.grace = max_t, grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        # rung thresholds (ascending)
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(int(t))
            t *= reduction_factor
        # rung -> list of recorded metric values
        self._recorded: Dict[int, List[float]] = {r: [] for r in self.rungs}
        # trial -> highest rung already judged (so a trial whose
        # time_attr jumps over a rung value still gets judged exactly
        # once per rung, at the first report past it)
        self._trial_rung: Dict[Any, int] = {}

    def _better(self, value: float, peers: List[float]) -> bool:
        """Is value in the top 1/rf quantile of peers (self included)?"""
        all_vals = sorted(peers + [value],
                          reverse=(self.mode == "max"))
        cutoff_idx = max(0, int(math.ceil(len(all_vals) / self.rf)) - 1)
        cutoff = all_vals[cutoff_idx]
        return (value >= cutoff) if self.mode == "max" else (value <= cutoff)

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        judged = self._trial_rung.get(trial, -1)
        for rung in reversed(self.rungs):
            # first report at-or-past a rung not yet judged for this
            # trial triggers the halving decision (exact equality let
            # trials whose time_attr skips rung values run to max_t)
            if t >= rung and rung > judged:
                self._trial_rung[trial] = rung
                peers = self._recorded[rung]
                keep = self._better(float(value), peers)
                peers.append(float(value))
                return CONTINUE if keep else STOP
        return CONTINUE

    def on_trial_complete(self, trial) -> None:
        self._trial_rung.pop(trial, None)


class PopulationBasedTraining:
    """PBT: at each perturbation interval, bottom-quantile trials clone a
    top-quantile trial's checkpoint and mutate its config (explore)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 2,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 time_attr: str = "training_iteration",
                 seed: int = 0):
        assert mode in ("min", "max")
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self.rng = random.Random(seed)
        self._last: Dict[Any, Dict[str, Any]] = {}   # trial -> last result

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        self._last[trial] = result
        t = result.get(self.time_attr, 0)
        if t == 0 or t % self.interval:
            return CONTINUE
        ranked = self._ranked_trials()
        if len(ranked) < 2:
            return CONTINUE
        n_q = max(1, int(len(ranked) * self.quantile))
        bottom = ranked[-n_q:]
        if trial in bottom and trial is not ranked[0]:
            return EXPLOIT
        return CONTINUE

    def exploit_target(self, trial):
        """Pick a top-quantile trial to clone from."""
        ranked = self._ranked_trials()
        n_q = max(1, int(len(ranked) * self.quantile))
        top = [t for t in ranked[:n_q] if t is not trial]
        return self.rng.choice(top) if top else None

    def explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Mutate hyperparams (reference: perturb by 0.8/1.2 or resample)."""
        from .search import Domain
        new = dict(config)
        for key, spec in self.mutations.items():
            if key not in new:
                continue
            if isinstance(spec, list):
                new[key] = self.rng.choice(spec)
            elif isinstance(spec, Domain):
                new[key] = spec.sample(self.rng)
            elif callable(spec):
                new[key] = spec()
            elif isinstance(new[key], (int, float)):
                factor = self.rng.choice((0.8, 1.2))
                new[key] = type(new[key])(new[key] * factor)
        return new

    def _ranked_trials(self) -> List[Any]:
        scored = [(t, r.get(self.metric)) for t, r in self._last.items()
                  if r.get(self.metric) is not None]
        return [t for t, v in sorted(
            scored, key=lambda kv: kv[1],
            reverse=(self.mode == "max"))]

    def on_trial_complete(self, trial) -> None:
        self._last.pop(trial, None)
