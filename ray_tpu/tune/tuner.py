"""Tuner + trial controller.

Reference: ``tune/tuner.py:59`` (Tuner.fit :337), controller
``tune/execution/tune_controller.py:81`` (trials as actors via the AIR
actor manager), experiment resume ``tune/execution/experiment_state.py``
+ ``Tuner.restore``.

Each trial is an actor running the trainable under a train-session; its
``report()`` stream feeds scheduler decisions (ASHA early stop, PBT
exploit/explore) and is journaled to ``experiment.json`` for resume.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional

from .. import get, kill, wait
from ..api import remote
from ..exceptions import TaskError, WorkerCrashedError
from ..train.checkpoint import Checkpoint
from ..train.config import RunConfig
from ..train.result import Result
from ..train.session import TrainContext, _set_session
from .result_grid import ResultGrid
from .schedulers import CONTINUE, EXPLOIT, STOP, FIFOScheduler
from .search import BasicVariantGenerator


@dataclasses.dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None
    search_seed: int = 0
    # a Searcher (tune.searcher) — when set, trials are created lazily
    # from its suggest() stream instead of BasicVariantGenerator
    search_alg: Any = None


@remote
class _TrialActor:
    def run(self, trainable: Callable, config: Dict[str, Any],
            queue, trial_id: str, resume_ckpt_path: Optional[str]):
        resume = Checkpoint(resume_ckpt_path) if resume_ckpt_path else None
        ctx = TrainContext(0, 1, _TaggedQueue(queue, trial_id), resume,
                           config=config, experiment_name=trial_id)
        _set_session(ctx)
        try:
            trainable(config)
        finally:
            _set_session(None)
        return trial_id


class _TaggedQueue:
    """Wraps the shared results queue, stamping payloads with trial id."""

    def __init__(self, queue, trial_id: str):
        self._q = queue
        self._tid = trial_id

    def put(self, payload):
        payload["trial_id"] = self._tid
        self._q.put(payload)


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.trial_id = trial_id
        self.config = config
        self.status = "PENDING"       # PENDING/RUNNING/TERMINATED/ERROR
        self.history: List[Dict[str, Any]] = []
        self.iteration = 0
        self.checkpoint_path: Optional[str] = None
        self.error: Optional[str] = None
        self.actor = None
        self.ref = None
        self.resume_from: Optional[str] = None

    def last_metrics(self) -> Dict[str, Any]:
        return self.history[-1] if self.history else {}

    def snapshot(self) -> dict:
        return {"trial_id": self.trial_id, "config": _jsonable(self.config),
                "status": self.status, "iteration": self.iteration,
                "checkpoint_path": self.checkpoint_path,
                "error": self.error, "history": _jsonable(self.history)}


def _jsonable(x):
    try:
        json.dumps(x)
        return x
    except (TypeError, ValueError):
        if isinstance(x, dict):
            return {k: _jsonable(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return [_jsonable(v) for v in x]
        return repr(x)


def _drain_reports(queue, by_id, exp_dir, scheduler, stop_trial, exploit,
                   launch, Empty, on_result=None) -> None:
    """Apply every queued report: record history, persist checkpoints,
    let the scheduler stop/exploit running trials."""
    while True:
        try:
            payload = queue.get_nowait()
        except Empty:
            return
        trial = by_id.get(payload.get("trial_id"))
        if trial is None:
            continue
        if trial.status != "RUNNING":
            # late reports from a stopped/exploited actor are dropped —
            # the reference's killed actors simply never send them
            continue
        metrics = payload["metrics"]
        trial.iteration += 1
        metrics.setdefault("training_iteration", trial.iteration)
        trial.history.append(metrics)
        if on_result is not None:
            on_result(trial, metrics)
        if payload.get("checkpoint_path"):
            src = payload["checkpoint_path"]
            dst = os.path.join(exp_dir, trial.trial_id,
                               f"checkpoint_{trial.iteration:06d}")
            if os.path.isdir(src):
                if os.path.exists(dst):
                    shutil.rmtree(dst)
                shutil.copytree(src, dst)
                trial.checkpoint_path = dst
        decision = scheduler.on_result(trial, metrics)
        if decision == STOP:
            stop_trial(trial, "TERMINATED")
        elif decision == EXPLOIT:
            exploit(trial, scheduler, launch, stop_trial)


class Tuner:
    def __init__(self, trainable: Callable, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune = tune_config or TuneConfig()
        self._run = run_config or RunConfig()
        self._restored_trials: Optional[List[Trial]] = None

    # ------------------------------------------------------------- restore
    @classmethod
    def restore(cls, path: str, trainable: Callable) -> "Tuner":
        with open(os.path.join(path, "experiment.json")) as f:
            state = json.load(f)
        tuner = cls(trainable,
                    tune_config=TuneConfig(**state["tune_config"]),
                    run_config=RunConfig(name=state["name"],
                                         storage_path=state["storage"]))
        trials = []
        for snap in state["trials"]:
            t = Trial(snap["trial_id"], snap["config"])
            t.history = snap["history"]
            t.iteration = snap["iteration"]
            t.checkpoint_path = snap["checkpoint_path"]
            # finished trials stay finished; others rerun from checkpoint
            if snap["status"] == "TERMINATED":
                t.status = "TERMINATED"
            else:
                t.status = "PENDING"
                t.resume_from = snap["checkpoint_path"]
        # (configs with non-json values were stringified — restore only
        # supports json-able param spaces, like the reference's json journal)
            trials.append(t)
        tuner._restored_trials = trials
        return tuner

    # ----------------------------------------------------------------- fit
    def fit(self) -> ResultGrid:
        from ..util.queue import Empty, Queue

        name = self._run.name or f"tune_{int(time.time())}"
        storage = self._run.storage_path or os.path.join(
            os.path.expanduser("~"), "rtpu_results")
        exp_dir = os.path.join(storage, name)
        os.makedirs(exp_dir, exist_ok=True)
        scheduler = self._tune.scheduler or FIFOScheduler()

        searcher = self._tune.search_alg
        if self._restored_trials is not None:
            trials = self._restored_trials
            searcher = None     # restored experiments rerun as journaled
        elif searcher is not None:
            from .searcher import FINISHED  # noqa: F401
            searcher.set_search_properties(
                self._tune.metric, self._tune.mode, self._param_space)
            trials = []         # created lazily from suggest()
        else:
            gen = BasicVariantGenerator(self._param_space,
                                        self._tune.num_samples,
                                        seed=self._tune.search_seed)
            trials = [Trial(f"{name}_{i:05d}", cfg)
                      for i, cfg in enumerate(gen.variants())]

        queue = Queue()
        by_id = {t.trial_id: t for t in trials}
        pending = [t for t in trials if t.status == "PENDING"]
        running: List[Trial] = []
        n_created = len(trials)
        search_done = searcher is None

        def launch(trial: Trial) -> None:
            trial.actor = _TrialActor.remote()
            trial.ref = trial.actor.run.remote(
                self._trainable, trial.config, queue, trial.trial_id,
                trial.resume_from)
            trial.status = "RUNNING"
            running.append(trial)

        def stop_trial(trial: Trial, status: str,
                       error: Optional[str] = None) -> None:
            trial.status = status
            trial.error = error
            if trial in running:
                running.remove(trial)
            if trial.actor is not None:
                try:
                    kill(trial.actor)
                except Exception:
                    pass
                trial.actor = None
            scheduler.on_trial_complete(trial)
            # PBT exploit re-launches as PENDING — not a completion
            if searcher is not None and status in ("TERMINATED", "ERROR"):
                searcher.on_trial_complete(
                    trial.trial_id, trial.last_metrics() or None,
                    error=status == "ERROR")

        def persist() -> None:
            state = {
                "name": name, "storage": storage,
                "tune_config": {
                    "metric": self._tune.metric, "mode": self._tune.mode,
                    "num_samples": self._tune.num_samples,
                    "max_concurrent_trials":
                        self._tune.max_concurrent_trials,
                    "search_seed": self._tune.search_seed,
                },
                "trials": [t.snapshot() for t in trials],
            }
            tmp = os.path.join(exp_dir, ".experiment.json.tmp")
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, os.path.join(exp_dir, "experiment.json"))
            if searcher is not None:
                try:
                    searcher.save(os.path.join(exp_dir,
                                               "searcher_state.pkl"))
                except Exception:
                    pass   # search state is best-effort, like the ref

        def on_result(trial: Trial, metrics: Dict[str, Any]) -> None:
            if searcher is not None:
                searcher.on_trial_result(trial.trial_id, metrics)

        while pending or running or not search_done:
            if not search_done:
                from .searcher import FINISHED
                while (n_created < self._tune.num_samples
                       and len(running) + len(pending)
                       < self._tune.max_concurrent_trials):
                    tid = f"{name}_{n_created:05d}"
                    cfg = searcher.suggest(tid)
                    if cfg is FINISHED:
                        search_done = True
                        break
                    if cfg is None:    # e.g. ConcurrencyLimiter at cap
                        break
                    t = Trial(tid, cfg)
                    trials.append(t)
                    by_id[tid] = t
                    pending.append(t)
                    n_created += 1
                if n_created >= self._tune.num_samples:
                    search_done = True
            while pending and len(running) < \
                    self._tune.max_concurrent_trials:
                launch(pending.pop(0))
            if not running and not pending and not search_done:
                time.sleep(0.02)   # searcher momentarily out of configs

            _drain_reports(queue, by_id, exp_dir, scheduler, stop_trial,
                           self._exploit, launch, Empty, on_result)

            # completed/failed trial actors. A finished actor's reports
            # are all queued before its run-ref resolves, so drain once
            # more after wait() and before marking trials TERMINATED —
            # otherwise the final report would be dropped as "late".
            refs = {t.ref: t for t in running if t.ref is not None}
            if refs:
                done, _ = wait(list(refs), num_returns=len(refs),
                               timeout=0.05)
                if done:
                    _drain_reports(queue, by_id, exp_dir, scheduler,
                                   stop_trial, self._exploit, launch,
                                   Empty, on_result)
                for ref in done:
                    trial = refs[ref]
                    if trial not in running:
                        continue
                    try:
                        get(ref)
                        stop_trial(trial, "TERMINATED")
                    except (TaskError, WorkerCrashedError) as e:
                        stop_trial(trial, "ERROR", error=str(e))
            persist()
        # final drain: reports can land between the last drain and the
        # trial-completion check that ended the loop
        _drain_reports(queue, by_id, exp_dir, scheduler, stop_trial,
                       self._exploit, launch, Empty, on_result)
        persist()
        try:
            queue.shutdown()
        except Exception:
            pass

        results = []
        for t in trials:
            ckpt = Checkpoint(t.checkpoint_path) if t.checkpoint_path \
                else None
            err = RuntimeError(t.error) if t.error else None
            results.append(Result(metrics=t.last_metrics(), checkpoint=ckpt,
                                  path=os.path.join(exp_dir, t.trial_id),
                                  error=err, metrics_history=t.history))
        return ResultGrid(results, metric=self._tune.metric,
                          mode=self._tune.mode)

    def _exploit(self, trial: Trial, scheduler, launch, stop_trial) -> None:
        """PBT exploit/explore: restart from a better trial's checkpoint
        with a mutated config."""
        target = scheduler.exploit_target(trial)
        if target is None or target.checkpoint_path is None:
            return
        stop_trial(trial, "PENDING")
        trial.config = scheduler.explore(dict(target.config))
        trial.resume_from = target.checkpoint_path
        launch(trial)
