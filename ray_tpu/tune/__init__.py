"""ray_tpu.tune — hyperparameter search over actor-run trials.

Reference: Ray Tune (``python/ray/tune/``, SURVEY §2.3): trials run as
actors, a controller schedules them against cluster resources
(``tune/execution/tune_controller.py:81``), searchers generate configs,
schedulers (ASHA ``schedulers/async_hyperband.py``, PBT ``pbt.py``) make
early-stop / exploit decisions on streamed results, experiment state is
resumable. Here trials are ray_tpu actors; a trial's training loop
reports through the same session machinery as ray_tpu.train, so a
JaxTrainer can be tuned unchanged.
"""

from .result_grid import ResultGrid  # noqa: F401
from .schedulers import (  # noqa: F401
    ASHAScheduler,
    FIFOScheduler,
    PopulationBasedTraining,
)
from .search import (  # noqa: F401
    BasicVariantGenerator,
    choice,
    grid_search,
    loguniform,
    qrandint,
    quniform,
    randint,
    sample_from,
    uniform,
)
from .searcher import (  # noqa: F401
    BasicVariantSearcher,
    ConcurrencyLimiter,
    OptunaSearcher,
    Searcher,
    TPESearcher,
)
from .tuner import TuneConfig, Tuner  # noqa: F401
from ..train.session import get_checkpoint, get_context, report  # noqa: F401
