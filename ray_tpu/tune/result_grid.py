"""ResultGrid (reference: ``tune/result_grid.py``)."""

from __future__ import annotations

from typing import List, Optional

from ..train.result import Result


class ResultGrid:
    def __init__(self, results: List[Result], metric: str = "loss",
                 mode: str = "min"):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self):
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self._results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        rows = []
        for r in self._results:
            row = dict(r.metrics)
            row["path"] = r.path
            rows.append(row)
        try:
            import pandas as pd
            return pd.DataFrame(rows)
        except ImportError:
            return rows
