"""User-facing exceptions.

Mirrors the semantic set of the reference's ``python/ray/exceptions.py``:
task errors that wrap remote tracebacks, actor death, object loss, and
cancellation — the names are our own.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception.

    Re-raised at ``get()`` on the caller, carrying the remote traceback as
    text (reference analogue: ``RayTaskError``,
    ``python/ray/exceptions.py``).
    """

    def __init__(self, cause_cls_name: str, cause_msg: str, traceback_str: str,
                 task_name: str = ""):
        self.cause_cls_name = cause_cls_name
        self.cause_msg = cause_msg
        self.traceback_str = traceback_str
        self.task_name = task_name
        super().__init__(self._format())

    def _format(self) -> str:
        return (
            f"task {self.task_name or '<unknown>'} failed with "
            f"{self.cause_cls_name}: {self.cause_msg}\n"
            f"--- remote traceback ---\n{self.traceback_str}"
        )

    def __reduce__(self):
        return (TaskError, (self.cause_cls_name, self.cause_msg,
                            self.traceback_str, self.task_name))


class ActorError(RayTpuError):
    """An actor task cannot run because the actor is dead or dying."""


class ActorDiedError(ActorError):
    def __init__(self, actor_id, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"actor {actor_id} died: {reason}")

    def __reduce__(self):
        return (ActorDiedError, (self.actor_id, self.reason))


class ObjectLostError(RayTpuError):
    """An object's value was lost from the store and cannot be recovered."""

    def __init__(self, object_id, reason: str = ""):
        self.object_id = object_id
        super().__init__(f"object {object_id} lost: {reason}")

    def __reduce__(self):
        return (ObjectLostError, (self.object_id,))


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"task {task_id} was cancelled")

    def __reduce__(self):
        return (TaskCancelledError, (self.task_id,))


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get()`` timed out before the object was available."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing a task died unexpectedly."""


class OutOfMemoryError(WorkerCrashedError):
    """The memory monitor killed the worker to relieve node memory
    pressure (reference analogue: ``ray.exceptions.OutOfMemoryError``)."""


class RuntimeEnvSetupError(RayTpuError):
    """Setting up the runtime environment for a task/actor failed."""


class PendingCallsLimitExceededError(RayTpuError):
    """Too many in-flight calls to an actor with a bounded queue."""
