"""Multi-node clusters for tests and tools: in-process or OS-isolated.

Equivalent role to the reference's ``ray.cluster_utils.Cluster``
(``python/ray/cluster_utils.py:108``) — the primary
multi-node-without-a-cluster mechanism (SURVEY §4): each ``add_node``
starts a full node service (its own scheduler, worker subprocess pool and
object store). Two modes:

- default: node services share one in-process control plane (fast, and
  every internal is introspectable from the test);
- ``process_isolated=True``: each node is a separate OS process joined
  over TCP through the GCS service (``_private/main.py``) — the same
  topology as a real multi-host deployment, with ``remove_node`` a
  genuine ``SIGKILL`` (chaos testing; reference analogue:
  ``Cluster`` + the node killer, ``_private/test_utils.py:1391``).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

from ._private.gcs import GlobalControlPlane
from ._private.node import NodeService


class RemoteNode:
    """Handle to a node running in its own OS process."""

    def __init__(self, proc: subprocess.Popen, ready: dict):
        self.proc = proc
        self.ready = ready            # full readiness record (ports etc.)
        self.node_id_hex: str = ready["node_id"]
        self.address: str = ready["node_address"]
        self.job_port = ready.get("job_port")

    @property
    def pid(self) -> int:
        return self.proc.pid


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None,
                 process_isolated: bool = False):
        self.process_isolated = process_isolated
        self.session_dir = tempfile.mkdtemp(prefix="rtpu_cluster_")
        self.nodes: list = []
        self.head = None
        self.gcs = None
        self.gcs_address: Optional[str] = None
        if not process_isolated:
            self.gcs = GlobalControlPlane()
        if initialize_head:
            self.head = self.add_node(**(head_node_args or {}))

    # ------------------------------------------------------------ members
    def add_node(self, num_cpus: int = 4, num_tpus: int = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 env: Optional[Dict[str, str]] = None):
        res = dict(resources or {})
        if self.process_isolated:
            return self._spawn_node(num_cpus, num_tpus, res, labels or {},
                                    extra_env=env)
        res.setdefault("CPU", float(num_cpus))
        if num_tpus:
            res.setdefault("TPU", float(num_tpus))
        node = NodeService(self.gcs, self.session_dir, res)
        node.start(labels=labels)
        self.nodes.append(node)
        if self.head is None:
            self.head = node
        return node

    def _spawn_node(self, num_cpus, num_tpus, resources, labels,
                    timeout: float = 30.0,
                    extra_env: Optional[Dict[str, str]] = None) -> RemoteNode:
        is_head = self.head is None
        ready_file = os.path.join(
            self.session_dir, f"ready_{len(self.nodes)}_{os.getpid()}.json")
        cmd = [sys.executable, "-m", "ray_tpu._private.main",
               "--num-cpus", str(num_cpus),
               "--resources", json.dumps(resources),
               "--labels", json.dumps(labels),
               "--session-dir", os.path.join(
                   self.session_dir, f"node_{len(self.nodes)}"),
               "--ready-file", ready_file]
        if num_tpus:
            cmd += ["--num-tpus", str(num_tpus)]
        if is_head:
            cmd += ["--head"]
        else:
            cmd += ["--address", self.gcs_address]
        env = dict(os.environ)
        # dev checkouts: the framework may be importable only via the
        # driver's cwd; installed builds need no path help
        from ._private.config import fw_importable_without_path
        fw_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        pp = env.get("PYTHONPATH", "")
        if (not fw_importable_without_path()
                and fw_root not in pp.split(os.pathsep)):
            env["PYTHONPATH"] = (pp + os.pathsep if pp else "") + fw_root
        if extra_env:
            env.update(extra_env)
        proc = subprocess.Popen(cmd, env=env)
        deadline = time.monotonic() + timeout
        while not os.path.exists(ready_file):
            if proc.poll() is not None:
                raise RuntimeError(
                    f"node process exited rc={proc.returncode} before ready")
            if time.monotonic() > deadline:
                proc.kill()
                raise TimeoutError("node process never became ready")
            time.sleep(0.05)
        with open(ready_file) as f:
            ready = json.load(f)
        node = RemoteNode(proc, ready)
        self.nodes.append(node)
        if is_head:
            self.head = node
            self.gcs_address = f"127.0.0.1:{ready['gcs_port']}"
        return node

    def remove_node(self, node, allow_graceful: bool = False) -> None:
        """Kill a node, simulating failure (reference analogue:
        ``Cluster.remove_node`` and the chaos node-killer,
        ``_private/test_utils.py:1391``)."""
        if isinstance(node, RemoteNode):
            if allow_graceful:
                node.proc.terminate()
            else:
                node.proc.kill()
            node.proc.wait(timeout=10)
        else:
            node.kill()
        if node in self.nodes:
            self.nodes.remove(node)

    def shutdown(self) -> None:
        for node in list(self.nodes):
            if isinstance(node, RemoteNode):
                node.proc.terminate()
            else:
                node.stop()
        for node in list(self.nodes):
            if isinstance(node, RemoteNode):
                try:
                    node.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    node.proc.kill()
        self.nodes.clear()
        import shutil
        shutil.rmtree(self.session_dir, ignore_errors=True)
