"""In-process multi-node cluster for tests and tools.

Equivalent role to the reference's ``ray.cluster_utils.Cluster``
(``python/ray/cluster_utils.py:108``) — the primary
multi-node-without-a-cluster mechanism (SURVEY §4): each ``add_node``
starts a full node service (its own scheduler, worker subprocess pool and
object store) sharing one control plane, so scheduling, placement-group
packing, object transfer and node-failure paths run for real on one
machine.
"""

from __future__ import annotations

import tempfile
from typing import Dict, List, Optional

from ._private.gcs import GlobalControlPlane
from ._private.node import NodeService


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self.gcs = GlobalControlPlane()
        self.session_dir = tempfile.mkdtemp(prefix="rtpu_cluster_")
        self.nodes: List[NodeService] = []
        self.head: Optional[NodeService] = None
        if initialize_head:
            self.head = self.add_node(**(head_node_args or {}))

    def add_node(self, num_cpus: int = 4, num_tpus: int = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None) -> NodeService:
        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus))
        if num_tpus:
            res.setdefault("TPU", float(num_tpus))
        node = NodeService(self.gcs, self.session_dir, res)
        node.start(labels=labels)
        self.nodes.append(node)
        if self.head is None:
            self.head = node
        return node

    def remove_node(self, node: NodeService, allow_graceful: bool = False) -> None:
        """Kill a node, simulating failure (reference analogue:
        ``Cluster.remove_node`` and the chaos node-killer,
        ``_private/test_utils.py:1391``)."""
        node.kill()
        if node in self.nodes:
            self.nodes.remove(node)

    def shutdown(self) -> None:
        for node in list(self.nodes):
            node.stop()
        self.nodes.clear()
        import shutil
        shutil.rmtree(self.session_dir, ignore_errors=True)
