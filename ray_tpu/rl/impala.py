"""IMPALA: asynchronous actor-critic with V-trace correction.

Reference: ``rllib/algorithms/impala/impala.py`` (async sample requests
kept in flight, learner consumes whatever arrived, weights broadcast
back to the workers that just reported) and
``rllib/core/learner/learner_group.py:61`` for the multi-learner form.
TPU-first shape: the V-trace update is ONE jitted program over stacked
time-major fragments (``Learner._vtrace_loss``); off-policy staleness
from async sampling is exactly what V-trace's rho/c clipping corrects.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import get, wait
from .env import CartPoleEnv
from .learner import Learner, LearnerGroup
from .module import DiscretePolicyModule
from .vector_env import EnvRunner
from . import sample_batch as SB


class ImpalaConfig:
    """Builder (reference: ``ImpalaConfig`` fluent API)."""

    def __init__(self):
        self.env_creator: Callable = CartPoleEnv
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 1
        self.rollout_fragment_length = 64
        self.lr = 5e-4
        self.gamma = 0.99
        self.entropy_coeff = 0.01
        self.vf_loss_coeff = 0.5
        self.grad_clip = 40.0
        self.clip_rho_threshold = 1.0
        self.clip_c_threshold = 1.0
        # passes over each collected batch (reference: minibatch_buffer's
        # num_sgd_iter; >1 reuses data, V-trace corrects the off-policy
        # drift this introduces)
        self.num_sgd_iter = 1
        self.hidden = (64, 64)
        self.num_learners = 0          # 0 = in-process learner
        self.seed = 0

    def environment(self, env_creator: Callable) -> "ImpalaConfig":
        self.env_creator = env_creator
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None
                 ) -> "ImpalaConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        return self

    def training(self, **kwargs) -> "ImpalaConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown IMPALA setting {k!r}")
            setattr(self, k, v)
        return self

    def learners(self, num_learners: int) -> "ImpalaConfig":
        self.num_learners = num_learners
        return self

    def build(self) -> "Impala":
        return Impala(self)


class Impala:
    def __init__(self, config: ImpalaConfig):
        self.config = config
        probe = config.env_creator()
        module_cfg = {"observation_size": probe.observation_size,
                      "action_size": probe.action_size,
                      "hidden": tuple(config.hidden)}
        self.module = DiscretePolicyModule(**module_cfg)
        learner_kwargs = dict(
            lr=config.lr, vf_coeff=config.vf_loss_coeff,
            entropy_coeff=config.entropy_coeff,
            grad_clip=config.grad_clip, gamma=config.gamma,
            rho_clip=config.clip_rho_threshold,
            c_clip=config.clip_c_threshold,
            loss="vtrace", seed=config.seed)
        if config.num_learners > 0:
            self.learner = LearnerGroup(self.module,
                                        num_learners=config.num_learners,
                                        **learner_kwargs)
        else:
            self.learner = Learner(self.module, **learner_kwargs)
        self.workers: List[Any] = [
            EnvRunner.remote(config.env_creator, module_cfg,
                             num_envs=config.num_envs_per_worker,
                             gamma=config.gamma, lam=1.0,
                             seed=config.seed + i * 1000)
            for i in range(config.num_rollout_workers)]
        # async pipeline: one sample request in flight per worker at all
        # times; train() consumes whatever is ready
        self._inflight: Dict[Any, Any] = {}       # ref -> worker
        weights = self.learner.get_weights()
        for w in self.workers:
            self._submit(w, weights)
        self.iteration = 0
        self._episodes_total = 0
        self._episodes_by_worker: Dict[int, int] = {}

    def _submit(self, worker, weights) -> None:
        ref = worker.sample.remote(weights,
                                   self.config.rollout_fragment_length,
                                   compute_advantages=False)
        self._inflight[ref] = worker

    # ---------------------------------------------------------------- train
    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        # at least one fragment, plus everything else already queued —
        # the async part: slow workers don't gate the learner
        ready, _ = wait(list(self._inflight), num_returns=1, timeout=None)
        more, _ = wait(list(set(self._inflight) - set(ready)),
                       num_returns=len(self._inflight) - len(ready),
                       timeout=0) if len(self._inflight) > len(ready) \
            else ([], [])
        done_refs = list(ready) + list(more)
        results = get(done_refs)
        finished_workers = [self._inflight.pop(r) for r in done_refs]

        # each runner reports [N, T, ...] fragments (N = envs/runner)
        frags = [b for b, _ in results]
        stats_list = [s for _, s in results]
        boot_list = [s["bootstrap_obs"] for s in stats_list]
        # pad B up to workers*envs by cycling ready fragments: a
        # constant batch shape keeps ONE compiled learner program
        # instead of a retrace per distinct fragment count (slight
        # overweighting of duplicated rows, same spirit as the
        # reference's batch bucketing)
        target_b = (self.config.num_rollout_workers
                    * self.config.num_envs_per_worker)
        i = 0
        while sum(f[SB.OBS].shape[0] for f in frags) < target_b:
            frags.append(frags[i % len(results)])
            boot_list.append(boot_list[i % len(results)])
            i += 1
        batch = {
            SB.OBS: np.concatenate([f[SB.OBS] for f in frags]),
            SB.ACTIONS: np.concatenate([f[SB.ACTIONS] for f in frags]),
            SB.REWARDS: np.concatenate([f[SB.REWARDS] for f in frags]),
            SB.DONES: np.concatenate([f[SB.DONES] for f in frags]),
            SB.LOGP: np.concatenate([f[SB.LOGP] for f in frags]),
            "bootstrap_obs": np.concatenate(boot_list),
        }
        learner_stats: Dict[str, float] = {}
        for _ in range(self.config.num_sgd_iter):
            learner_stats = self.learner.update(SB.SampleBatch(batch))
        # broadcast the fresh weights only to the workers that reported
        # (the reference's broadcast-on-report async weight sync)
        weights = self.learner.get_weights()
        for w in finished_workers:
            self._submit(w, weights)

        self.iteration += 1
        rewards = [s["episode_reward_mean"] for s in stats_list
                   if not np.isnan(s["episode_reward_mean"])]
        # per-worker counts are cumulative: the cluster total is the sum
        # of each worker's latest report (matches PPO's semantics)
        for w, s in zip(finished_workers, stats_list):
            self._episodes_by_worker[id(w)] = s["episodes_total"]
        self._episodes_total = sum(self._episodes_by_worker.values())
        sampled = (len(results) * self.config.num_envs_per_worker
                   * self.config.rollout_fragment_length)
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": (float(np.mean(rewards)) if rewards
                                    else float("nan")),
            "episodes_total": self._episodes_total,
            "num_env_steps_sampled": sampled,
            "num_env_steps_trained": sampled,
            "time_this_iter_s": time.perf_counter() - t0,
            **{f"learner/{k}": v for k, v in learner_stats.items()},
        }

    def get_weights(self):
        return self.learner.get_weights()

    def stop(self) -> None:
        from .. import kill
        for w in self.workers:
            try:
                kill(w)
            except Exception:
                pass
        if isinstance(self.learner, LearnerGroup):
            self.learner.shutdown()
