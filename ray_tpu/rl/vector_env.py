"""Vectorized environments + the unified env-runner actor.

Reference: ``rllib/env/vector_env.py`` (VectorEnv — N sub-envs stepped
as a batch with auto-reset) and ``rllib/env/env_runner.py`` (the one
runner abstraction all algorithms sample through). TPU-first shape:
the policy is evaluated ONCE per step for all N sub-envs — a [N, obs]
batched jitted call — so dispatch overhead amortizes and the batch dim
feeds the MXU, instead of N scalar forward passes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..api import remote
from . import sample_batch as SB
from .module import DiscretePolicyModule, QNetworkModule
from .sample_batch import SampleBatch, compute_gae

NEXT_OBS = "next_obs"


class VectorEnv:
    """N sub-environments stepped together with per-env auto-reset."""

    def __init__(self, env_creator: Callable, num_envs: int,
                 seed: Optional[int] = None):
        if num_envs < 1:
            raise ValueError("num_envs must be >= 1")
        import inspect
        takes_seed = False
        try:
            takes_seed = "seed" in inspect.signature(
                env_creator).parameters
        except (TypeError, ValueError):
            pass
        self.envs = []
        for i in range(num_envs):
            if takes_seed:
                self.envs.append(env_creator(
                    seed=None if seed is None else seed + i))
            else:
                self.envs.append(env_creator())
        self.num_envs = num_envs
        probe = self.envs[0]
        self.observation_size = probe.observation_size
        self.action_size = probe.action_size

    def reset_all(self) -> np.ndarray:
        return np.stack([e.reset()[0] for e in self.envs]).astype(
            np.float32)

    def step(self, actions: np.ndarray):
        """Step every sub-env; done envs auto-reset. Returns
        (obs[N,D] AFTER auto-reset, rewards[N], terminateds[N],
        truncateds[N], final_obs[N,D] BEFORE any reset) — consumers
        needing the pre-reset observation (DQN's next_obs, truncation
        bootstrapping) read ``final_obs``."""
        n = self.num_envs
        obs = np.empty((n, self.observation_size), np.float32)
        final = np.empty((n, self.observation_size), np.float32)
        rewards = np.empty(n, np.float32)
        terms = np.empty(n, bool)
        truncs = np.empty(n, bool)
        for i, (env, a) in enumerate(zip(self.envs, actions)):
            o, r, term, trunc, _ = env.step(int(a))
            final[i] = o
            rewards[i] = r
            terms[i] = term
            truncs[i] = trunc
            if term or trunc:
                o, _ = env.reset()
            obs[i] = o
        return obs, rewards, terms, truncs, final


@remote
class EnvRunner:
    """The one sampling actor every algorithm uses (reference:
    ``rllib/env/env_runner.py``): a VectorEnv plus a batched jitted
    policy head. ``sample`` serves the on-policy family (PPO flat+GAE,
    IMPALA time-major fragments); ``sample_epsilon_greedy`` serves the
    off-policy family (DQN transitions with next_obs)."""

    def __init__(self, env_creator: Callable, module_config: dict, *,
                 num_envs: int = 1, module_kind: str = "policy",
                 gamma: float = 0.99, lam: float = 0.95, seed: int = 0):
        import jax
        self.venv = VectorEnv(env_creator, num_envs, seed=seed)
        self.gamma = gamma
        self.lam = lam
        self.num_envs = num_envs
        self._rng = jax.random.PRNGKey(seed)
        self._np_rng = np.random.default_rng(seed)
        self._obs: Optional[np.ndarray] = None
        self._episode_reward = np.zeros(num_envs, np.float64)
        self._episode_rewards: List[float] = []
        if module_kind == "policy":
            self.module = DiscretePolicyModule(**module_config)

            def _act_impl(params, obs, rng):
                rng, key = jax.random.split(rng)
                action, logp, value = self.module.action_dist(
                    params, obs, key)
                return action, logp, value, rng

            self._act = jax.jit(_act_impl)
            self._value = jax.jit(
                lambda p, o: self.module.forward(p, o)[1])
        else:
            self.module = QNetworkModule(**module_config)
            self._q = jax.jit(self.module.forward)

    # ------------------------------------------------------- policy mode
    def sample(self, weights, num_steps: int,
               compute_advantages: bool = True
               ) -> Tuple[dict, dict]:
        """Collect ``num_steps`` transitions PER SUB-ENV.

        compute_advantages=True (PPO): flat env-major batch of
        N*num_steps rows with per-env GAE columns.
        compute_advantages=False (IMPALA): time-major per-env fragments
        — arrays shaped [N, T, ...] plus stats["bootstrap_obs"] [N, D].
        """
        import jax
        params = jax.tree_util.tree_map(jax.numpy.asarray, weights)
        if self._obs is None:
            self._obs = self.venv.reset_all()
        n, horizon = self.num_envs, num_steps
        obs_b = np.empty((horizon, n, self.venv.observation_size),
                         np.float32)
        act_b = np.empty((horizon, n), np.int32)
        rew_b = np.empty((horizon, n), np.float32)
        done_b = np.empty((horizon, n), bool)
        logp_b = np.empty((horizon, n), np.float32)
        vf_b = np.empty((horizon, n), np.float32)
        for t in range(horizon):
            action, logp, value, self._rng = self._act(
                params, self._obs, self._rng)
            acts = np.asarray(action)
            nxt, rewards, terms, truncs, final = self.venv.step(acts)
            cut = truncs & ~terms
            if cut.any():
                # truncated (not finished) episodes: fold the bootstrap
                # into the final reward so marking done stays unbiased
                boot = np.asarray(self._value(params, final))
                rewards = rewards + np.where(
                    cut, self.gamma * boot, 0.0).astype(np.float32)
            obs_b[t] = self._obs
            act_b[t] = acts
            rew_b[t] = rewards
            done_b[t] = terms | truncs
            logp_b[t] = np.asarray(logp)
            vf_b[t] = np.asarray(value)
            self._episode_reward += np.asarray(rewards, np.float64)
            for i in np.nonzero(terms | truncs)[0]:
                self._episode_rewards.append(
                    float(self._episode_reward[i]))
                self._episode_reward[i] = 0.0
            self._obs = nxt
        recent = self._episode_rewards[-20:]
        stats = {
            "episodes_total": len(self._episode_rewards),
            "episode_reward_mean": (float(np.mean(recent))
                                    if recent else float("nan")),
            # [N, D]: off-policy learners bootstrap each fragment from
            # its own env's next observation
            "bootstrap_obs": np.asarray(self._obs, np.float32),
        }
        if not compute_advantages:
            batch = {                       # env-major time series
                SB.OBS: obs_b.swapaxes(0, 1),
                SB.ACTIONS: act_b.T,
                SB.REWARDS: rew_b.T,
                SB.DONES: done_b.T,
                SB.LOGP: logp_b.T,
            }
            return batch, stats
        # PPO: per-env GAE, then flatten env-major
        frags = []
        last_values = np.asarray(self._value(params, self._obs))
        for i in range(n):
            frag = SampleBatch({
                SB.OBS: obs_b[:, i], SB.ACTIONS: act_b[:, i],
                SB.REWARDS: rew_b[:, i], SB.DONES: done_b[:, i],
                SB.LOGP: logp_b[:, i], SB.VF_PREDS: vf_b[:, i],
            })
            last = 0.0 if done_b[-1, i] else float(last_values[i])
            frags.append(compute_gae(frag, gamma=self.gamma,
                                     lam=self.lam, last_value=last))
        out = {k: np.concatenate([dict(f)[k] for f in frags])
               for k in dict(frags[0])}
        return out, stats

    # ------------------------------------------------ epsilon-greedy mode
    def sample_epsilon_greedy(self, weights, num_steps: int,
                              epsilon: float) -> Tuple[dict, dict]:
        """DQN collection: flat transitions with next_obs; exploration
        by per-env epsilon-greedy over one batched Q forward."""
        import jax
        params = jax.tree_util.tree_map(jax.numpy.asarray, weights)
        if self._obs is None:
            self._obs = self.venv.reset_all()
        n = self.num_envs
        rows_obs, rows_next = [], []
        rows_act, rows_rew, rows_done = [], [], []
        for _ in range(num_steps):
            q = np.asarray(self._q(params, self._obs))
            acts = q.argmax(axis=-1)
            explore = self._np_rng.random(n) < epsilon
            acts = np.where(
                explore,
                self._np_rng.integers(0, self.venv.action_size, n),
                acts)
            nxt, rewards, terms, truncs, final = self.venv.step(acts)
            rows_obs.append(self._obs.copy())
            rows_next.append(final)
            rows_act.append(acts.astype(np.int32))
            rows_rew.append(rewards)
            rows_done.append(terms)     # truncation is not a terminal
            self._episode_reward += np.asarray(rewards, np.float64)
            for i in np.nonzero(terms | truncs)[0]:
                self._episode_rewards.append(
                    float(self._episode_reward[i]))
                self._episode_reward[i] = 0.0
            self._obs = nxt
        batch = {
            SB.OBS: np.concatenate(rows_obs),
            SB.ACTIONS: np.concatenate(rows_act),
            SB.REWARDS: np.concatenate(rows_rew),
            NEXT_OBS: np.concatenate(rows_next),
            SB.DONES: np.concatenate(rows_done),
        }
        rewards, self._episode_rewards = self._episode_rewards, []
        stats = {"episode_rewards": rewards}
        return batch, stats

    def collect_epsilon_greedy(self, weights, num_steps: int,
                               epsilon: float):
        """DQN replay-plane form: the batch goes STRAIGHT to the object
        store from this actor; only the ref travels (the buffer actor
        holds refs, never payloads)."""
        from .. import put
        batch, stats = self.sample_epsilon_greedy(weights, num_steps,
                                                  epsilon)
        count = int(len(batch[SB.ACTIONS]))
        return [put(batch)], count, stats
