"""Replay buffer library.

Reference: ``rllib/utils/replay_buffers/`` (ReplayBuffer,
PrioritizedReplayBuffer with proportional sampling + importance
weights, per Schaul et al. 2016). TPU-native shape: buffers are plain
objects usable in-process OR as actors (``.as_remote()``); stored
items are whole SampleBatch fragments whose payloads live in the
object store when used through the actor form — the buffer actor holds
refs and priorities, never megabytes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class ReplayBuffer:
    """Uniform-sampling FIFO ring of items (transitions or fragments)."""

    def __init__(self, capacity: int, seed: Optional[int] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._items: List[Any] = []
        self._next = 0
        self._rng = np.random.default_rng(seed)
        self.num_added = 0

    def __len__(self) -> int:
        return len(self._items)

    def add(self, item: Any) -> None:
        if len(self._items) < self.capacity:
            self._items.append(item)
        else:
            self._items[self._next] = item
        self._next = (self._next + 1) % self.capacity
        self.num_added += 1

    def sample(self, n: int) -> List[Any]:
        """n items uniformly with replacement (empty buffer -> [])."""
        if not self._items:
            return []
        idx = self._rng.integers(0, len(self._items), size=n)
        return [self._items[i] for i in idx]

    def stats(self) -> Dict[str, Any]:
        return {"size": len(self._items), "num_added": self.num_added,
                "capacity": self.capacity}

    @classmethod
    def as_remote(cls, **actor_options):
        """The same buffer as a zero-CPU actor class (reference:
        actor-hosted replay in RLlib)."""
        from ..api import remote
        return remote(num_cpus=0, **actor_options)(cls)


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    ``prioritized_replay_buffer.py``; Schaul et al. 2016).

    ``sample`` draws with probability p_i^alpha / sum p^alpha and
    returns importance weights w_i = (N * P(i))^-beta normalized by
    max w; ``update_priorities`` feeds TD errors back.
    """

    def __init__(self, capacity: int, *, alpha: float = 0.6,
                 seed: Optional[int] = None):
        super().__init__(capacity, seed=seed)
        if alpha < 0:
            raise ValueError("alpha must be >= 0")
        self.alpha = alpha
        self._prios = np.zeros(capacity, dtype=np.float64)
        self._max_prio = 1.0

    def add(self, item: Any, priority: Optional[float] = None) -> None:
        slot = (len(self._items) if len(self._items) < self.capacity
                else self._next)
        super().add(item)
        # same signed-TD normalization as update_priorities: raw TD
        # errors are signed, and a negative base under fractional alpha
        # would go complex
        p = (float(abs(priority)) + 1e-6 if priority is not None
             else self._max_prio)
        self._max_prio = max(self._max_prio, p)
        self._prios[slot] = p ** self.alpha

    def sample(self, n: int, beta: float = 0.4
               ) -> Tuple[List[Any], np.ndarray, np.ndarray]:
        """Returns (items, indices, importance_weights)."""
        size = len(self._items)
        if not size:
            return [], np.asarray([], np.int64), np.asarray([])
        p = self._prios[:size]
        total = p.sum()
        probs = (p / total) if total > 0 else np.full(size, 1.0 / size)
        idx = self._rng.choice(size, size=n, p=probs)
        weights = (size * probs[idx]) ** (-beta)
        weights = weights / weights.max()
        return [self._items[i] for i in idx], idx, weights

    def update_priorities(self, indices: np.ndarray,
                          priorities: np.ndarray) -> None:
        for i, p in zip(np.asarray(indices), np.asarray(priorities)):
            p = float(abs(p)) + 1e-6
            self._max_prio = max(self._max_prio, p)
            if 0 <= int(i) < len(self._items):
                self._prios[int(i)] = p ** self.alpha
