"""Environments (gym-style API without the gym dependency).

Reference: ``rllib/env/`` — the API subset algorithms need:
``reset() -> (obs, info)``, ``step(a) -> (obs, reward, terminated,
truncated, info)``. CartPole matches the classic control task
(reference tuned example: PPO CartPole-v1, BASELINE.json config #1).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


class Env:
    observation_size: int
    action_size: int

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action: int):
        raise NotImplementedError


class CartPoleEnv(Env):
    """CartPole-v1 dynamics (pole balancing; reward 1/step, cap 500)."""

    observation_size = 4
    action_size = 2

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)
        self._state = None
        self._steps = 0

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        return self._state.astype(np.float32).copy(), {}

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        cos, sin = np.cos(theta), np.sin(theta)
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        temp = (force + pole_ml * theta_dot ** 2 * sin) / total_mass
        theta_acc = (self.GRAVITY * sin - cos * temp) / (
            self.POLE_HALF_LEN * (4.0 / 3.0 - self.POLE_MASS * cos ** 2
                                  / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos / total_mass
        x += self.DT * x_dot
        x_dot += self.DT * x_acc
        theta += self.DT * theta_dot
        theta_dot += self.DT * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._steps += 1
        terminated = bool(abs(x) > self.X_LIMIT
                          or abs(theta) > self.THETA_LIMIT)
        truncated = self._steps >= self.MAX_STEPS
        return (self._state.astype(np.float32).copy(), 1.0, terminated,
                truncated, {})


class RandomEnv(Env):
    """Reference analogue: ``rllib/examples/env/random_env.py`` — smoke
    tests without meaningful dynamics."""

    def __init__(self, observation_size: int = 4, action_size: int = 2,
                 episode_len: int = 10, seed: Optional[int] = None):
        self.observation_size = observation_size
        self.action_size = action_size
        self.episode_len = episode_len
        self._rng = np.random.default_rng(seed)
        self._steps = 0

    def reset(self, seed: Optional[int] = None):
        self._steps = 0
        return self._rng.normal(size=self.observation_size).astype(
            np.float32), {}

    def step(self, action: int):
        self._steps += 1
        obs = self._rng.normal(size=self.observation_size).astype(
            np.float32)
        return (obs, float(self._rng.normal()), False,
                self._steps >= self.episode_len, {})
