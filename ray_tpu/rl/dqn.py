"""DQN: the minimal off-policy family member.

Reference: ``rllib/algorithms/dqn/`` (replay buffer + target network +
epsilon-greedy collection). TPU-native mapping:

  * The REPLAY PLANE is the object store: rollout actors ``put`` each
    collected fragment and register only the ObjectRef with the replay
    buffer actor, so replay data lives in shm — the buffer actor holds
    refs, never payloads (reference: replay buffers are actor-hosted,
    ``rllib/utils/replay_buffers/``; here zero-copy via the store).
  * The learner's update (double-DQN TD loss + optax step + periodic
    target sync) is one jitted program.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import get, put, remote
from . import sample_batch as SB
from .module import QNetworkModule
from .sample_batch import SampleBatch, concat_batches

NEXT_OBS = "next_obs"


@remote(num_cpus=0)
class ReplayBuffer:
    """Holds ObjectRefs of transition fragments (the payloads stay in
    the object store); uniform sampling over stored fragments. Capacity
    is in TRANSITIONS; oldest fragments are dropped (their store blocks
    free via refcounting once unreferenced)."""

    def __init__(self, capacity: int, seed: int = 0):
        self._capacity = capacity
        self._frags: List[tuple] = []        # ([ref], n_transitions)
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def add(self, wrapped_ref, count: int) -> int:
        self._frags.append((wrapped_ref, count))
        self._size += count
        while self._size - self._frags[0][1] >= self._capacity \
                and len(self._frags) > 1:
            _, n = self._frags.pop(0)
            self._size -= n
        return self._size

    def size(self) -> int:
        return self._size

    def sample_refs(self, n_fragments: int) -> List[Any]:
        """Random fragments (with replacement) — the learner fetches the
        payloads itself, so replay bytes never route through this
        actor."""
        if not self._frags:
            return []
        idx = self._rng.integers(0, len(self._frags), size=n_fragments)
        return [self._frags[i][0] for i in idx]


class DQNLearner:
    """Jitted double-DQN update + periodic target sync."""

    def __init__(self, module: QNetworkModule, *, lr: float = 1e-3,
                 gamma: float = 0.99, target_update_freq: int = 200,
                 huber_delta: float = 1.0, seed: int = 0):
        import jax
        import optax

        self.module = module
        self.gamma = gamma
        self.huber_delta = huber_delta
        self.target_update_freq = target_update_freq
        self.params = module.init(jax.random.PRNGKey(seed))
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)
        self.opt = optax.adam(lr)
        self.opt_state = self.opt.init(self.params)
        self._updates = 0
        self._step = jax.jit(self._update_impl)

    def _loss(self, params, target_params, batch):
        import jax
        import jax.numpy as jnp

        q = self.module.forward(params, batch[SB.OBS])
        q_sa = q[jnp.arange(q.shape[0]), batch[SB.ACTIONS]]
        # double DQN: online net picks a', target net evaluates it
        q_next_online = self.module.forward(params, batch[NEXT_OBS])
        a_next = jnp.argmax(q_next_online, axis=-1)
        q_next_target = self.module.forward(target_params,
                                            batch[NEXT_OBS])
        q_next = q_next_target[jnp.arange(a_next.shape[0]), a_next]
        not_done = 1.0 - batch[SB.DONES].astype(jnp.float32)
        target = batch[SB.REWARDS] + self.gamma * not_done * \
            jax.lax.stop_gradient(q_next)
        td = q_sa - target
        # Huber loss (reference: DQN's clipped TD error)
        d = self.huber_delta
        loss = jnp.where(jnp.abs(td) <= d, 0.5 * td ** 2,
                         d * (jnp.abs(td) - 0.5 * d)).mean()
        return loss, {"td_error_mean": jnp.abs(td).mean(), "loss": loss}

    def _update_impl(self, params, target_params, opt_state, batch):
        import jax
        import optax

        grads, metrics = jax.grad(
            lambda p: self._loss(p, target_params, batch),
            has_aux=True)(params)
        updates, opt_state = self.opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, metrics

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, metrics = self._step(
            self.params, self.target_params, self.opt_state, jb)
        self._updates += 1
        if self._updates % self.target_update_freq == 0:
            import jax
            self.target_params = jax.tree_util.tree_map(
                lambda x: x, self.params)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        import jax
        return jax.tree_util.tree_map(np.asarray, self.params)


class DQNConfig:
    """Builder mirroring the PPO/IMPALA config surface (reference:
    ``AlgorithmConfig`` chaining)."""

    def __init__(self):
        self.env_creator: Optional[Callable] = None
        self.num_rollout_workers = 1
        self.num_envs_per_worker = 1
        self.fragment_length = 128
        self.hidden = (64, 64)
        self.lr = 1e-3
        self.gamma = 0.99
        self.train_batch_size = 64
        self.updates_per_iter = 64
        self.buffer_capacity = 50_000
        self.learning_starts = 1_000
        self.target_update_freq = 200
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 4_000
        self.seed = 0

    def environment(self, env_creator: Callable) -> "DQNConfig":
        self.env_creator = env_creator
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None
                 ) -> "DQNConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if rollout_fragment_length is not None:
            self.fragment_length = rollout_fragment_length
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        return self

    def training(self, **kwargs) -> "DQNConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown DQN training option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "DQN":
        if self.env_creator is None:
            raise ValueError("call .environment(env_creator) first")
        return DQN(self)


class DQN:
    """Iterate ``train()``: collect with decaying epsilon → replay →
    minibatch double-DQN updates (reference: ``dqn.py`` training_step —
    sample, store, replay, update-target)."""

    def __init__(self, config: DQNConfig):
        env = config.env_creator()
        module_config = {"observation_size": env.observation_size,
                         "action_size": env.action_size,
                         "hidden": config.hidden}
        self.config = config
        self.module = QNetworkModule(**module_config)
        self.learner = DQNLearner(
            self.module, lr=config.lr, gamma=config.gamma,
            target_update_freq=config.target_update_freq,
            seed=config.seed)
        self.buffer = ReplayBuffer.remote(config.buffer_capacity,
                                          seed=config.seed)
        from .vector_env import EnvRunner
        self.workers = [
            EnvRunner.remote(config.env_creator, module_config,
                             num_envs=config.num_envs_per_worker,
                             module_kind="q", seed=config.seed + i * 1000)
            for i in range(config.num_rollout_workers)]
        self._steps_sampled = 0
        self._rng = np.random.default_rng(config.seed)
        self._episode_rewards: List[float] = []

    # ----------------------------------------------------------- train
    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._steps_sampled / max(1, c.epsilon_decay_steps))
        return c.epsilon_initial + frac * (c.epsilon_final
                                           - c.epsilon_initial)

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.perf_counter()
        weights = self.learner.get_weights()
        eps = self._epsilon()
        outs = get([w.collect_epsilon_greedy.remote(
                        weights, c.fragment_length, eps)
                    for w in self.workers])
        adds = []
        for wrapped, count, stats in outs:
            self._steps_sampled += count
            self._episode_rewards.extend(stats["episode_rewards"])
            adds.append(self.buffer.add.remote(wrapped, count))
        buffer_size = max(get(adds)) if adds else 0

        metrics: Dict[str, float] = {}
        n_updates = 0
        if buffer_size >= min(c.learning_starts, c.buffer_capacity):
            frag_refs = get(self.buffer.sample_refs.remote(
                c.updates_per_iter))
            for wrapped in frag_refs:
                frag = SampleBatch(get(wrapped[0]))
                idx = self._rng.integers(0, len(frag),
                                         size=c.train_batch_size)
                mb = SampleBatch({k: v[idx] for k, v in frag.items()})
                metrics = self.learner.update(mb)
                n_updates += 1

        recent = self._episode_rewards[-20:]
        return {
            "num_env_steps_sampled": self._steps_sampled,
            "num_updates": n_updates,
            "buffer_size": buffer_size,
            "epsilon": round(eps, 4),
            "episode_reward_mean": (float(np.mean(recent))
                                    if recent else float("nan")),
            "time_this_iter_s": time.perf_counter() - t0,
            **metrics,
        }

    def get_weights(self):
        return self.learner.get_weights()

    def stop(self) -> None:
        from .. import kill
        for w in self.workers:
            try:
                kill(w)
            except Exception:
                pass
        try:
            kill(self.buffer)
        except Exception:
            pass
