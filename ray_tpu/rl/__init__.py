"""ray_tpu.rl — reinforcement learning at scale (the RLlib equivalent).

Reference: RLlib (``rllib/``, SURVEY §2.3/§3.6) new stack: `Algorithm`
owns rollout workers (env sampling actors) and a `LearnerGroup` of
learner actors for SGD. TPU-native mapping:

  * EnvRunner actors run (vectorized) envs on CPU hosts and evaluate
    the policy
    with jitted JAX on host devices — sampling never touches the TPU.
  * The Learner's update is ONE jitted SPMD program (loss + grad + optax)
    over a device mesh; multi-learner data-parallelism is mesh `dp`, not
    NCCL DDP (reference wraps ``TorchLearner`` in DDP,
    ``core/learner/torch/torch_learner.py:378``).
  * Weights move learner→workers through the shm object store.

Built-in envs avoid a gym dependency (CartPole dynamics are 20 lines).
"""

from .env import CartPoleEnv, RandomEnv  # noqa: F401
from .impala import Impala, ImpalaConfig  # noqa: F401
from .learner import Learner, LearnerGroup  # noqa: F401
from .module import DiscretePolicyModule  # noqa: F401
from .ppo import PPO, PPOConfig  # noqa: F401
from .sample_batch import SampleBatch, concat_batches  # noqa: F401
from .dqn import DQN, DQNConfig, ReplayBuffer  # noqa: F401
from .module import QNetworkModule  # noqa: F401
from .vector_env import EnvRunner, VectorEnv  # noqa: F401
from .replay_buffers import (  # noqa: F401
    PrioritizedReplayBuffer,
    ReplayBuffer as UniformReplayBuffer,
)
from .offline import OfflineDQN, collect_to_dataset  # noqa: F401
