"""RLModule: the policy/value network in functional JAX.

Reference: ``rllib/core/rl_module/rl_module.py:229`` (+ the minimal JAX
FCNet the reference already sketches at ``rllib/models/jax/fcnet.py``).
One module = params pytree + pure apply functions; the same params run
jitted on TPU (learner) and on CPU (rollout workers).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _dense_init(key, n_in, n_out, scale):
    w_key, _ = jax.random.split(key)
    # orthogonal init (PPO standard)
    a = jax.random.normal(w_key, (n_in, n_out))
    q, r = jnp.linalg.qr(a)
    q = q * jnp.sign(jnp.diag(r))[None, :]
    if q.shape != (n_in, n_out):
        q = jnp.resize(q, (n_in, n_out))
    return {"w": (q * scale).astype(jnp.float32),
            "b": jnp.zeros((n_out,), jnp.float32)}


class DiscretePolicyModule:
    """MLP torso + categorical policy head + value head."""

    def __init__(self, observation_size: int, action_size: int,
                 hidden: Tuple[int, ...] = (64, 64)):
        self.observation_size = observation_size
        self.action_size = action_size
        self.hidden = tuple(hidden)

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        keys = jax.random.split(rng, len(self.hidden) + 2)
        params: Dict[str, Any] = {"torso": []}
        n_in = self.observation_size
        for i, h in enumerate(self.hidden):
            params["torso"].append(_dense_init(keys[i], n_in, h,
                                               math.sqrt(2.0)))
            n_in = h
        params["pi"] = _dense_init(keys[-2], n_in, self.action_size, 0.01)
        params["vf"] = _dense_init(keys[-1], n_in, 1, 1.0)
        return params

    def _torso(self, params, obs):
        x = obs
        for layer in params["torso"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        return x

    def forward(self, params, obs) -> Tuple[jax.Array, jax.Array]:
        """obs [B, obs_size] → (logits [B, A], value [B])."""
        x = self._torso(params, obs)
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return logits, value

    def action_dist(self, params, obs, rng) -> Tuple[jax.Array, jax.Array,
                                                     jax.Array]:
        """Sample actions: (action, logp, value)."""
        logits, value = self.forward(params, obs)
        action = jax.random.categorical(rng, logits)
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(logits.shape[0]), action]
        return action, logp, value


class QNetworkModule:
    """MLP state-action value network: obs -> Q[B, A] (reference:
    DQN's default model — same torso family as the policy module)."""

    def __init__(self, observation_size: int, action_size: int,
                 hidden: Tuple[int, ...] = (64, 64)):
        self.observation_size = observation_size
        self.action_size = action_size
        self.hidden = tuple(hidden)

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        keys = jax.random.split(rng, len(self.hidden) + 1)
        params: Dict[str, Any] = {"torso": []}
        n_in = self.observation_size
        for i, h in enumerate(self.hidden):
            params["torso"].append(_dense_init(keys[i], n_in, h,
                                               math.sqrt(2.0)))
            n_in = h
        params["q"] = _dense_init(keys[-1], n_in, self.action_size, 0.01)
        return params

    def forward(self, params, obs) -> jax.Array:
        x = obs
        for layer in params["torso"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        return x @ params["q"]["w"] + params["q"]["b"]
