"""Offline RL: train from logged transitions in a Dataset.

Reference: ``rllib/offline/`` (JsonReader/DatasetReader feeding
off-policy algorithms without environment interaction). Here the input
is a ``ray_tpu.data.Dataset`` whose rows carry obs/actions/rewards/
next_obs/dones columns — written by ``collect_to_dataset`` below or
any ETL — and the learner is the same jitted double-DQN update the
online algorithm uses.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from . import sample_batch as SB
from .dqn import NEXT_OBS, DQNLearner
from .module import QNetworkModule
from .sample_batch import SampleBatch

REQUIRED = (SB.OBS, SB.ACTIONS, SB.REWARDS, NEXT_OBS, SB.DONES)


class OfflineDQN:
    """Double-DQN trained purely from a logged-transition Dataset."""

    def __init__(self, dataset, *, observation_size: int,
                 action_size: int, hidden=(64, 64), lr: float = 1e-3,
                 gamma: float = 0.99, target_update_freq: int = 200,
                 train_batch_size: int = 64, seed: int = 0):
        self._blocks = [blk for blk in dataset.iter_blocks()
                        if blk and len(next(iter(blk.values())))]
        if not self._blocks:
            raise ValueError("offline dataset is empty")
        for blk in self._blocks:     # every block: heterogeneous ETL
            missing = [c for c in REQUIRED if c not in blk]
            if missing:
                raise ValueError(
                    f"offline dataset lacks columns {missing}; needs "
                    f"{list(REQUIRED)}")
        self.module = QNetworkModule(observation_size, action_size,
                                     hidden=tuple(hidden))
        self.learner = DQNLearner(
            self.module, lr=lr, gamma=gamma,
            target_update_freq=target_update_freq, seed=seed)
        self.train_batch_size = train_batch_size
        self._rng = np.random.default_rng(seed)
        self._updates = 0

    def _minibatch(self) -> SampleBatch:
        blk = self._blocks[self._rng.integers(0, len(self._blocks))]
        n = len(blk[SB.ACTIONS])
        idx = self._rng.integers(0, n, size=self.train_batch_size)
        return SampleBatch({c: np.asarray(blk[c])[idx]
                            for c in REQUIRED})

    def train(self, num_updates: int = 64) -> Dict[str, Any]:
        metrics: Dict[str, float] = {}
        for _ in range(num_updates):
            metrics = self.learner.update(self._minibatch())
            self._updates += 1
        return {"num_updates": self._updates, **metrics}

    def get_weights(self):
        return self.learner.get_weights()


def collect_to_dataset(env_creator, *, num_steps: int,
                       num_envs: int = 4, epsilon: float = 1.0,
                       seed: int = 0, weights: Optional[Any] = None,
                       hidden=(64, 64)):
    """Log transitions from an (epsilon-greedy) behavior policy into a
    Dataset (reference: ``rllib/offline/output_writer.py`` — here the
    sink is the data plane itself)."""
    from ..data import from_numpy
    from .vector_env import EnvRunner

    cfg = _probe_module_config(env_creator, hidden)
    runner = EnvRunner.remote(env_creator, cfg, num_envs=num_envs,
                              module_kind="q", seed=seed)
    from .. import get, kill
    if weights is None:
        weights = _init_weights(cfg, seed)
    batch, _ = get(runner.sample_epsilon_greedy.remote(
        weights, num_steps, epsilon))
    try:
        kill(runner)
    except Exception:   # noqa: BLE001 — collection actor teardown
        pass
    return from_numpy({k: np.asarray(v) for k, v in batch.items()},
                      num_blocks=max(1, num_steps // 256))


def _probe_module_config(env_creator, hidden) -> Dict[str, Any]:
    env = env_creator()
    return {"observation_size": env.observation_size,
            "action_size": env.action_size, "hidden": tuple(hidden)}


def _init_weights(cfg, seed):
    import jax
    module = QNetworkModule(**cfg)
    return jax.tree_util.tree_map(
        np.asarray, module.init(jax.random.PRNGKey(seed)))
