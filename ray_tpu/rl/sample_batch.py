"""SampleBatch: columnar rollout data (reference:
``rllib/policy/sample_batch.py:98``)."""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"


class SampleBatch(dict):
    """Dict of equal-length numpy arrays."""

    def __len__(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @property
    def count(self) -> int:
        return len(self)

    def shuffle(self, seed=None) -> "SampleBatch":
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(self))
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = len(self)
        for lo in range(0, n - size + 1, size):
            yield SampleBatch({k: v[lo:lo + size]
                               for k, v in self.items()})

    def slice(self, lo: int, hi: int) -> "SampleBatch":
        return SampleBatch({k: v[lo:hi] for k, v in self.items()})


def concat_batches(batches: Sequence[SampleBatch]) -> SampleBatch:
    batches = [b for b in batches if len(b)]
    if not batches:
        return SampleBatch()
    keys = batches[0].keys()
    return SampleBatch({k: np.concatenate([b[k] for b in batches])
                        for k in keys})


def compute_gae(batch: SampleBatch, *, gamma: float = 0.99,
                lam: float = 0.95,
                last_value: float = 0.0) -> SampleBatch:
    """Generalized advantage estimation over a (possibly multi-episode)
    trajectory; ``dones`` cuts bootstrapping (reference:
    ``rllib/evaluation/postprocessing.py`` compute_advantages)."""
    rewards = batch[REWARDS]
    values = batch[VF_PREDS]
    dones = batch[DONES].astype(np.float32)
    n = len(rewards)
    adv = np.zeros(n, np.float32)
    next_value = last_value
    next_adv = 0.0
    for t in range(n - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        next_adv = delta + gamma * lam * nonterminal * next_adv
        adv[t] = next_adv
        next_value = values[t]
    out = SampleBatch(batch)
    out[ADVANTAGES] = adv
    out[VALUE_TARGETS] = (adv + values).astype(np.float32)
    return out
