"""RolloutWorker: env-sampling actor.

Reference: ``rllib/evaluation/rollout_worker.py:159`` + SyncSampler
``evaluation/sampler.py:144``. Policy evaluation is jitted JAX on the
worker's host devices; env stepping is plain python — the hot loop the
reference also runs in python workers.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..api import remote
from . import sample_batch as SB
from .module import DiscretePolicyModule
from .sample_batch import SampleBatch, compute_gae


@remote
class RolloutWorker:
    def __init__(self, env_creator: Callable, module_config: dict,
                 *, gamma: float = 0.99, lam: float = 0.95,
                 seed: int = 0):
        import jax
        self.env = env_creator()
        self.module = DiscretePolicyModule(**module_config)
        self.gamma = gamma
        self.lam = lam
        self._rng = jax.random.PRNGKey(seed)

        # rng split folded into the jitted call: one dispatch per env
        # step instead of two (the sampling hot loop is dispatch-bound)
        def _act_impl(params, obs, rng):
            rng, key = jax.random.split(rng)
            action, logp, value = self.module.action_dist(params, obs, key)
            return action, logp, value, rng

        self._act = jax.jit(_act_impl)
        self._value = jax.jit(
            lambda p, o: self.module.forward(p, o)[1])
        self._obs: Optional[np.ndarray] = None
        self._episode_reward = 0.0
        self._episode_rewards = []

    def sample(self, weights, num_steps: int,
               compute_advantages: bool = True) -> Tuple[dict, dict]:
        """Collect num_steps transitions (episodes continue across
        calls); returns (SampleBatch dict, stats). With
        ``compute_advantages`` the batch carries GAE columns (PPO);
        off-policy consumers (V-trace) pass False and postprocess
        learner-side."""
        import jax
        params = jax.tree_util.tree_map(jax.numpy.asarray, weights)
        if self._obs is None:
            self._obs, _ = self.env.reset()
            self._episode_reward = 0.0
        obs_buf, act_buf, rew_buf, done_buf = [], [], [], []
        logp_buf, vf_buf = [], []
        for _ in range(num_steps):
            action, logp, value, self._rng = self._act(
                params, self._obs[None, :], self._rng)
            a = int(action[0])
            next_obs, reward, terminated, truncated, _ = self.env.step(a)
            obs_buf.append(self._obs)
            act_buf.append(a)
            rew_buf.append(reward)
            logp_buf.append(float(logp[0]))
            vf_buf.append(float(value[0]))
            self._episode_reward += reward
            if terminated or truncated:
                if truncated and not terminated:
                    # episode CUT, not finished: fold the bootstrap into
                    # the final reward so marking done stays unbiased —
                    # otherwise the value stream leaks across the reset
                    # into the next episode's fresh obs
                    rew_buf[-1] += self.gamma * float(
                        self._value(params, next_obs[None, :])[0])
                done_buf.append(True)
                self._episode_rewards.append(self._episode_reward)
                self._obs, _ = self.env.reset()
                self._episode_reward = 0.0
            else:
                done_buf.append(False)
                self._obs = next_obs
        batch = SampleBatch({
            SB.OBS: np.asarray(obs_buf, np.float32),
            SB.ACTIONS: np.asarray(act_buf, np.int32),
            SB.REWARDS: np.asarray(rew_buf, np.float32),
            SB.DONES: np.asarray(done_buf, bool),
            SB.LOGP: np.asarray(logp_buf, np.float32),
            SB.VF_PREDS: np.asarray(vf_buf, np.float32),
        })
        if compute_advantages:
            # bootstrap value for the unfinished tail
            last_value = 0.0
            if not done_buf[-1]:
                last_value = float(self._value(params,
                                               self._obs[None, :])[0])
            batch = compute_gae(batch, gamma=self.gamma, lam=self.lam,
                                last_value=last_value)
        recent = self._episode_rewards[-20:]
        stats = {
            "episodes_total": len(self._episode_rewards),
            "episode_reward_mean": (float(np.mean(recent))
                                    if recent else float("nan")),
            # obs following the last step: off-policy learners (V-trace)
            # bootstrap from it with their CURRENT value function
            "bootstrap_obs": np.asarray(self._obs, np.float32),
        }
        return dict(batch), stats
