"""Learner / LearnerGroup: SGD as one jitted SPMD program.

Reference: ``rllib/core/learner/learner.py:229`` (update :1230),
``learner_group.py:61``. The reference data-parallelizes learners with
torch DDP over NCCL; here a single jitted update runs over a device
mesh (dp axis) — multi-chip gradient psum is inside the program. The
LearnerGroup actor form exists for placement (run the learner on a TPU
host while rollouts run elsewhere), not for gradient plumbing.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..api import remote
from . import sample_batch as SB
from .module import DiscretePolicyModule


class Learner:
    """PPO-style clipped surrogate learner (the loss fn is pluggable)."""

    def __init__(self, module: DiscretePolicyModule,
                 *, lr: float = 3e-4, clip: float = 0.2,
                 vf_coeff: float = 0.5, entropy_coeff: float = 0.0,
                 grad_clip: float = 0.5, seed: int = 0,
                 gamma: float = 0.99,
                 rho_clip: float = 1.0, c_clip: float = 1.0,
                 loss: str = "ppo",
                 loss_fn: Optional[Callable] = None):
        self.module = module
        self.clip = clip
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.gamma = gamma
        self.rho_clip = rho_clip
        self.c_clip = c_clip
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(grad_clip), optax.adam(lr))
        self.params = module.init(jax.random.PRNGKey(seed))
        self.opt_state = self.optimizer.init(self.params)
        # `loss` is a picklable name so LearnerGroup actors can build the
        # same learner remotely; `loss_fn` overrides with a callable
        builtin = {"ppo": self._ppo_loss, "vtrace": self._vtrace_loss}
        self._loss_fn = loss_fn or builtin[loss]
        self._update = jax.jit(self._update_impl)

    # --------------------------------------------------------------- losses
    def _ppo_loss(self, params, batch) -> Tuple[jax.Array, Dict]:
        logits, values = self.module.forward(params, batch[SB.OBS])
        logp_all = jax.nn.log_softmax(logits)
        actions = batch[SB.ACTIONS]
        logp = logp_all[jnp.arange(actions.shape[0]), actions]
        ratio = jnp.exp(logp - batch[SB.LOGP])
        adv = batch[SB.ADVANTAGES]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg1 = ratio * adv
        pg2 = jnp.clip(ratio, 1 - self.clip, 1 + self.clip) * adv
        pg_loss = -jnp.minimum(pg1, pg2).mean()
        vf_loss = 0.5 * ((values - batch[SB.VALUE_TARGETS]) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        loss = (pg_loss + self.vf_coeff * vf_loss
                - self.entropy_coeff * entropy)
        stats = {"pg_loss": pg_loss, "vf_loss": vf_loss,
                 "entropy": entropy, "total_loss": loss,
                 "approx_kl": (batch[SB.LOGP] - logp).mean()}
        return loss, stats

    def _vtrace_loss(self, params, batch) -> Tuple[jax.Array, Dict]:
        """IMPALA's V-trace off-policy actor-critic loss over time-major
        fragments (reference: ``rllib/algorithms/impala`` + the V-trace
        targets of Espeholt et al. 2018). Batch layout: obs (B,T,D),
        actions/rewards/dones/action_logp (B,T), bootstrap_obs (B,D).
        The backward recursion is a ``lax.scan`` over time — one compiled
        program, no Python loop."""
        obs = batch[SB.OBS]
        bsz, horizon = obs.shape[0], obs.shape[1]
        logits, values = self.module.forward(
            params, obs.reshape(bsz * horizon, -1))
        logits = logits.reshape(bsz, horizon, -1)
        values = values.reshape(bsz, horizon)
        logp_all = jax.nn.log_softmax(logits)
        actions = batch[SB.ACTIONS]
        tlogp = jnp.take_along_axis(logp_all, actions[..., None],
                                    axis=-1)[..., 0]
        rho = jnp.exp(tlogp - batch[SB.LOGP])
        rho_c = jnp.minimum(rho, self.rho_clip)
        cs = jnp.minimum(rho, self.c_clip)
        _, bootstrap = self.module.forward(params, batch["bootstrap_obs"])
        discounts = self.gamma * (1.0 - batch[SB.DONES].astype(jnp.float32))
        values_tp1 = jnp.concatenate(
            [values[:, 1:], bootstrap[:, None]], axis=1)
        rewards = batch[SB.REWARDS]
        deltas = rho_c * (rewards + discounts * values_tp1 - values)

        def backward(acc, xs):
            delta_t, disc_t, c_t = xs
            acc = delta_t + disc_t * c_t * acc
            return acc, acc

        _, acc_rev = jax.lax.scan(
            backward, jnp.zeros(bsz),
            (deltas.T[::-1], discounts.T[::-1], cs.T[::-1]))
        vs = values + acc_rev[::-1].T                       # (B,T)
        vs_tp1 = jnp.concatenate([vs[:, 1:], bootstrap[:, None]], axis=1)
        pg_adv = jax.lax.stop_gradient(
            rho_c * (rewards + discounts * vs_tp1 - values))
        pg_loss = -(tlogp * pg_adv).mean()
        vf_loss = 0.5 * ((jax.lax.stop_gradient(vs) - values) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        loss = (pg_loss + self.vf_coeff * vf_loss
                - self.entropy_coeff * entropy)
        stats = {"pg_loss": pg_loss, "vf_loss": vf_loss,
                 "entropy": entropy, "total_loss": loss,
                 "mean_rho": rho.mean()}
        return loss, stats

    # --------------------------------------------------------------- update
    def _update_impl(self, params, opt_state, batch):
        (loss, stats), grads = jax.value_and_grad(
            self._loss_fn, has_aux=True)(params, batch)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, stats

    def update(self, batch: SB.SampleBatch) -> Dict[str, float]:
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, jbatch)
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)


@remote
class _LearnerActor:
    def __init__(self, module_config: dict, learner_kwargs: dict):
        module = DiscretePolicyModule(**module_config)
        self.learner = Learner(module, **learner_kwargs)

    def update(self, batch) -> Dict[str, float]:
        return self.learner.update(SB.SampleBatch(batch))

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)


class LearnerGroup:
    """Placement wrapper: run the learner on its own (TPU-host) actor.

    num_learners>1 splits each batch and averages weights after update —
    only useful multi-host; on one slice prefer one learner with a dp
    mesh (SPMD does the averaging exactly via gradient psum).
    """

    def __init__(self, module: DiscretePolicyModule, *,
                 num_learners: int = 1,
                 resources_per_learner: Optional[dict] = None,
                 **learner_kwargs):
        opts = {}
        if resources_per_learner:
            res = dict(resources_per_learner)
            if "CPU" in res:
                opts["num_cpus"] = res.pop("CPU")
            if res:
                opts["resources"] = res
        cfg = {"observation_size": module.observation_size,
               "action_size": module.action_size,
               "hidden": module.hidden}
        self._actors = [
            _LearnerActor.options(**opts).remote(cfg, learner_kwargs)
            for _ in range(num_learners)]

    def update(self, batch: SB.SampleBatch) -> Dict[str, float]:
        from .. import get
        b = len(batch)
        # never hand a learner an empty slice: with fewer rows than
        # learners (async algorithms often deliver a single fragment)
        # only the first len(batch) actors participate this round
        parts = self._actors[:max(1, min(len(self._actors), b))]
        n = len(parts)
        if n == 1:
            stats = [get(parts[0].update.remote(dict(batch)))]
        else:
            size = b // n
            refs = []
            for i, a in enumerate(parts):
                hi = b if i == n - 1 else (i + 1) * size
                refs.append(a.update.remote(dict(batch.slice(i * size,
                                                             hi))))
            stats = get(refs)
        if len(self._actors) > 1:
            # data-parallel consensus over the participants, broadcast
            # to everyone (non-participants hold pre-update weights)
            weights = get([a.get_weights.remote() for a in parts])
            mean_w = jax.tree_util.tree_map(
                lambda *ws: np.mean(np.stack(ws), axis=0), *weights)
            get([a.set_weights.remote(mean_w) for a in self._actors])
        return {k: float(np.mean([s[k] for s in stats]))
                for k in stats[0]}

    def get_weights(self):
        from .. import get
        return get(self._actors[0].get_weights.remote())

    def shutdown(self) -> None:
        from .. import kill
        for a in self._actors:
            try:
                kill(a)
            except Exception:
                pass
