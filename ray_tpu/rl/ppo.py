"""PPO algorithm: rollout fan-out → GAE → minibatch SGD epochs.

Reference: ``rllib/algorithms/ppo/ppo.py:420`` (training_step:
synchronous_parallel_sample over the WorkerSet → LearnerGroup.update →
weight broadcast) and ``algorithm_config.py`` (builder-style config).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import get
from .env import CartPoleEnv
from .learner import Learner, LearnerGroup
from .module import DiscretePolicyModule
from .vector_env import EnvRunner
from .sample_batch import SampleBatch, concat_batches


class PPOConfig:
    """Builder (reference: ``AlgorithmConfig`` fluent API)."""

    def __init__(self):
        self.env_creator: Callable = CartPoleEnv
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 1
        self.rollout_fragment_length = 256
        self.num_sgd_iter = 8
        self.sgd_minibatch_size = 128
        self.lr = 3e-4
        self.gamma = 0.99
        self.lam = 0.95
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.grad_clip = 0.5
        self.hidden = (64, 64)
        self.num_learners = 0          # 0 = in-process learner
        self.seed = 0

    def environment(self, env_creator: Callable) -> "PPOConfig":
        self.env_creator = env_creator
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None
                 ) -> "PPOConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        return self

    def training(self, **kwargs) -> "PPOConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown PPO setting {k!r}")
            setattr(self, k, v)
        return self

    def learners(self, num_learners: int) -> "PPOConfig":
        self.num_learners = num_learners
        return self

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    def __init__(self, config: PPOConfig):
        self.config = config
        probe = config.env_creator()
        module_cfg = {"observation_size": probe.observation_size,
                      "action_size": probe.action_size,
                      "hidden": tuple(config.hidden)}
        self.module = DiscretePolicyModule(**module_cfg)
        learner_kwargs = dict(lr=config.lr, clip=config.clip_param,
                              vf_coeff=config.vf_loss_coeff,
                              entropy_coeff=config.entropy_coeff,
                              grad_clip=config.grad_clip,
                              seed=config.seed)
        if config.num_learners > 0:
            self.learner = LearnerGroup(self.module,
                                        num_learners=config.num_learners,
                                        **learner_kwargs)
        else:
            self.learner = Learner(self.module, **learner_kwargs)
        self.workers: List[Any] = [
            EnvRunner.remote(config.env_creator, module_cfg,
                             num_envs=config.num_envs_per_worker,
                             gamma=config.gamma, lam=config.lam,
                             seed=config.seed + i * 1000)
            for i in range(config.num_rollout_workers)]
        self.iteration = 0

    # ---------------------------------------------------------------- train
    def train(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        weights = self.learner.get_weights()
        results = get([w.sample.remote(weights,
                                       cfg.rollout_fragment_length)
                       for w in self.workers])
        batch = concat_batches([SampleBatch(b) for b, _ in results])
        stats_list = [s for _, s in results]
        sgd_stats: Dict[str, float] = {}
        for _ in range(cfg.num_sgd_iter):
            shuffled = batch.shuffle(seed=self.iteration)
            for mb in shuffled.minibatches(cfg.sgd_minibatch_size):
                sgd_stats = self.learner.update(mb)
        self.iteration += 1
        rewards = [s["episode_reward_mean"] for s in stats_list
                   if not np.isnan(s["episode_reward_mean"])]
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": (float(np.mean(rewards)) if rewards
                                    else float("nan")),
            "episodes_total": sum(s["episodes_total"]
                                  for s in stats_list),
            "num_env_steps_sampled": (cfg.rollout_fragment_length
                                      * cfg.num_envs_per_worker
                                      * len(self.workers)),
            "time_this_iter_s": time.perf_counter() - t0,
            **{f"learner/{k}": v for k, v in sgd_stats.items()},
        }

    def get_weights(self):
        return self.learner.get_weights()

    def stop(self) -> None:
        from .. import kill
        for w in self.workers:
            try:
                kill(w)
            except Exception:
                pass
        if isinstance(self.learner, LearnerGroup):
            self.learner.shutdown()
