"""Runtime context introspection (reference:
``python/ray/runtime_context.py`` — get_runtime_context)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ._private import context


@dataclass
class RuntimeContext:
    job_id: object
    worker_id: object
    node_id: Optional[object]
    task_id: Optional[object]
    actor_id: Optional[object]
    in_worker: bool
    accel_ids: Optional[list] = None

    def get_accelerator_ids(self) -> dict:
        """Per-instance accelerator slots assigned to this task/actor
        (reference: ``RuntimeContext.get_accelerator_ids`` — GPU ids);
        empty on the driver or for fractional/zero demands."""
        return {"TPU": list(self.accel_ids or [])}

    def get_job_id(self):
        return self.job_id

    def get_worker_id(self):
        return self.worker_id

    def get_node_id(self):
        return self.node_id

    def get_task_id(self):
        return self.task_id

    def get_actor_id(self):
        return self.actor_id


def get_runtime_context() -> RuntimeContext:
    client = context.require_client()
    return RuntimeContext(
        job_id=client.job_id,
        worker_id=client.worker_id,
        node_id=getattr(client, "node_id", None),
        task_id=context.current_task_id,
        actor_id=context.current_actor_id,
        in_worker=context.in_worker,
        accel_ids=context.current_accel_ids,
    )
