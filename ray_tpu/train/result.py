"""Result of a training run (reference: ``air/result.py``)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from .checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    path: Optional[str]
    error: Optional[Exception] = None
    metrics_history: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint
