"""ray_tpu.train — distributed training orchestration, TPU-first.

Reference: Ray Train (``python/ray/train/``, SURVEY §2.3/§3.4). The
reference spawns N single-GPU worker processes and wires them into a
torch NCCL process group; TPU-native the unit of placement is the *host*
(4 chips each) and the unit of computation is ONE jitted SPMD program
over a `jax.sharding.Mesh` covering the slice — so `JaxTrainer` gangs
one worker actor per host, assembles a global mesh (jax.distributed on
real pods, local devices in tests), and runs the user's
``train_loop_per_worker`` in lockstep on every host.

Parallelism (dp/fsdp/tp/sp/pp/ep) is a `MeshSpec` in ScalingConfig, not
a wrapper class — see ``ray_tpu.parallel``.
"""

from .checkpoint import Checkpoint  # noqa: F401
from .config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from .result import Result  # noqa: F401
from .session import (  # noqa: F401
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from .trainer import JaxTrainer  # noqa: F401
