"""Per-worker training session (reference: ``train/_internal/session.py``
— ``_TrainSession.report`` :612; user API ``ray.train.report`` /
``get_context()``).

Workers call ``report(metrics, checkpoint=...)`` each epoch/interval;
results stream back to the trainer through a driver-owned results queue.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from .checkpoint import Checkpoint

_session_local = threading.local()


class TrainContext:
    def __init__(self, world_rank: int, world_size: int,
                 results_queue, latest_checkpoint: Optional[Checkpoint],
                 config: Optional[Dict[str, Any]] = None,
                 storage_path: Optional[str] = None,
                 experiment_name: str = "train",
                 dataset_shards: Optional[Dict[str, Any]] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.results_queue = results_queue
        self.latest_checkpoint = latest_checkpoint
        self.config = config or {}
        self.storage_path = storage_path
        self.experiment_name = experiment_name
        self.dataset_shards = dataset_shards or {}
        self.iteration = 0

    def get_dataset_shard(self, name: str = "train"):
        """This worker's streaming shard of the trainer's ``datasets``
        (reference: ``ray.train.get_dataset_shard``); a
        ``data.DataIterator`` — iterate ``iter_device_batches(...)`` to
        feed the step function."""
        try:
            return self.dataset_shards[name]
        except KeyError:
            raise KeyError(
                f"no dataset {name!r} was passed to JaxTrainer(datasets=...)"
                f"; have {sorted(self.dataset_shards)}") from None

    # reference: ray.train.get_context() surface
    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.world_rank   # one worker per host

    def get_trial_name(self) -> str:
        return self.experiment_name


def _set_session(ctx: Optional[TrainContext]) -> None:
    _session_local.ctx = ctx


def get_context() -> TrainContext:
    ctx = getattr(_session_local, "ctx", None)
    if ctx is None:
        raise RuntimeError(
            "not inside a train session (call from train_loop_per_worker)")
    return ctx


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) for this iteration.

    Rank 0's checkpoint is persisted; other ranks' checkpoints are
    ignored (TPU SPMD state is replicated or resharded on restore, so
    one host's copy suffices — pass fully-addressable trees).
    """
    ctx = get_context()
    ctx.iteration += 1
    payload = {
        "rank": ctx.world_rank,
        "iteration": ctx.iteration,
        "metrics": dict(metrics),
        "checkpoint_path": None,
    }
    if checkpoint is not None and ctx.world_rank == 0:
        checkpoint.set_metrics(metrics)
        payload["checkpoint_path"] = checkpoint.path
        ctx.latest_checkpoint = checkpoint
    ctx.results_queue.put(payload)


def get_checkpoint() -> Optional[Checkpoint]:
    """Latest checkpoint to resume from (set on restart after failure)."""
    return get_context().latest_checkpoint


def get_dataset_shard(name: str = "train"):
    """Module-level convenience (reference: ``ray.train.get_dataset_shard``)."""
    return get_context().get_dataset_shard(name)
