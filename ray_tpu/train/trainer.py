"""JaxTrainer — gang-scheduled SPMD training driver.

Reference flow (SURVEY §3.4): ``BaseTrainer.fit``
(``train/base_trainer.py:608``) → ``BackendExecutor``
(``_internal/backend_executor.py:46``) → ``WorkerGroup``
(``_internal/worker_group.py:101``) spawns N worker actors in a
placement-group gang, sets up a torch process group, runs
``train_loop_per_worker``, streams ``session.report`` results back.

TPU-native differences:
  * one worker per *host*, not per chip; inside each worker the user
    builds (or receives) a `jax.sharding.Mesh` over the host's devices —
    on a real pod `jax.distributed.initialize` stitches hosts into one
    global mesh (multi-controller SPMD); no NCCL/TCPStore rendezvous.
  * parallelism comes from `ScalingConfig.mesh` (a MeshSpec), not from
    DDP/FSDP wrapper classes.
  * failure handling is checkpoint-based elastic restart: on worker
    death the whole gang restarts from the last reported checkpoint
    (SPMD programs can't lose a single participant).
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional

from .. import get, kill, wait
from ..api import remote
from ..exceptions import TaskError, WorkerCrashedError
from ..util.placement_group import placement_group, remove_placement_group
from ..util.scheduling_strategies import PlacementGroupSchedulingStrategy
from .checkpoint import Checkpoint
from .config import (CheckpointConfig, FailureConfig, RunConfig,
                     ScalingConfig)
from .result import Result
from .session import TrainContext, _set_session


@remote
class _TrainWorker:
    """One gang member; executes the user loop under a session."""

    def __init__(self, rank: int, world_size: int, storage_path: str,
                 experiment_name: str):
        self.rank = rank
        self.world_size = world_size
        self.storage_path = storage_path
        self.experiment_name = experiment_name

    def run(self, loop_fn: Callable, config: Dict[str, Any],
            results_queue, resume_ckpt_path: Optional[str],
            dataset_shards: Optional[Dict[str, Any]] = None):
        resume = (Checkpoint(resume_ckpt_path)
                  if resume_ckpt_path else None)
        ctx = TrainContext(self.rank, self.world_size, results_queue,
                           resume, config=config,
                           storage_path=self.storage_path,
                           experiment_name=self.experiment_name,
                           dataset_shards=dataset_shards)
        _set_session(ctx)
        try:
            if _loop_takes_config(loop_fn):
                loop_fn(config)
            else:
                loop_fn()
        finally:
            _set_session(None)
        return self.rank


def _loop_takes_config(fn: Callable) -> bool:
    import inspect
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False
    return len([p for p in params.values()
                if p.default is inspect.Parameter.empty
                and p.kind in (p.POSITIONAL_ONLY,
                               p.POSITIONAL_OR_KEYWORD)]) >= 1


class JaxTrainer:
    """Run ``train_loop_per_worker`` on a gang of host workers.

    train_loop_per_worker: callable taking (config) or (); uses
    ``ray_tpu.train.report`` / ``get_checkpoint`` / ``get_context``.
    """

    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        self._loop = train_loop_per_worker
        self._loop_config = train_loop_config or {}
        self._scaling = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        self._failure = self._run_config.failure_config or FailureConfig()
        self._ckpt_config = (self._run_config.checkpoint_config
                             or CheckpointConfig())
        # {name: ray_tpu.data.Dataset} — each split into one streaming
        # shard per worker at fit() (and again per elastic restart);
        # workers consume via session.get_dataset_shard(name)
        # (reference: Train datasets= + data_config.py streaming split)
        self._datasets = datasets or {}

    # ------------------------------------------------------------------ fit
    def fit(self) -> Result:
        from ..util.queue import Queue

        name = self._run_config.name or "jax_train"
        storage = self._run_config.storage_path or os.path.join(
            os.path.expanduser("~"), "rtpu_results")
        exp_dir = os.path.join(storage, name)
        os.makedirs(exp_dir, exist_ok=True)

        attempts = 0
        latest_ckpt: Optional[Checkpoint] = None
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        saved_ckpts: List[str] = []
        error: Optional[Exception] = None

        while True:
            queue = Queue()
            gang = self._spawn_gang(name, storage)
            # fresh streaming shards per attempt: the pipeline re-executes
            # from the start on an elastic restart
            shard_sets = {
                ds_name: ds.streaming_split(self._scaling.num_workers)
                for ds_name, ds in self._datasets.items()}
            try:
                refs = [w.run.remote(self._loop, self._loop_config, queue,
                                     latest_ckpt.path if latest_ckpt
                                     else None,
                                     {ds_name: shards[rank]
                                      for ds_name, shards
                                      in shard_sets.items()})
                        for rank, w in enumerate(gang["workers"])]
                pending = list(refs)
                while pending:
                    _drain(queue, exp_dir, saved_ckpts, self._ckpt_config,
                           history)
                    latest_ckpt, last_metrics = _latest(history, latest_ckpt,
                                                        last_metrics)
                    done, pending = wait(pending,
                                         num_returns=len(pending),
                                         timeout=0.05)
                    for ref in done:
                        get(ref)        # surface worker exceptions
                _drain(queue, exp_dir, saved_ckpts, self._ckpt_config,
                       history)
                latest_ckpt, last_metrics = _latest(history, latest_ckpt,
                                                    last_metrics)
                error = None
                break
            except (TaskError, WorkerCrashedError) as e:
                # capture reports that landed before the crash — the last
                # checkpoint is the restart point
                try:
                    _drain(queue, exp_dir, saved_ckpts, self._ckpt_config,
                           history)
                    latest_ckpt, last_metrics = _latest(
                        history, latest_ckpt, last_metrics)
                except Exception:
                    pass
                attempts += 1
                budget = self._failure.max_failures
                if budget >= 0 and attempts > budget:
                    error = e
                    break
                # elastic restart from last checkpoint
            finally:
                self._teardown_gang(gang)
                try:
                    queue.shutdown()
                except Exception:
                    pass
                # shard queues + their feeder threads must die with the
                # attempt, or elastic restarts leak a queue-actor set
                # (and the pinned block refs inside) per retry
                for shards in shard_sets.values():
                    for shard in shards:
                        shard.shutdown()

        # surface the persisted copy of the final checkpoint if any
        final_ckpt = Checkpoint(saved_ckpts[-1]) if saved_ckpts else \
            latest_ckpt
        return Result(metrics=last_metrics, checkpoint=final_ckpt,
                      path=exp_dir, error=error,
                      metrics_history=[h["metrics"] for h in history
                                       if h["rank"] == 0])

    # ------------------------------------------------------------- plumbing
    def _spawn_gang(self, name: str, storage: str) -> dict:
        sc = self._scaling
        bundle = sc.bundle()
        pg = placement_group([bundle] * sc.num_workers,
                             strategy=sc.placement_strategy)
        try:
            pg.ready(timeout=60.0)
        except TimeoutError:
            if sc.placement_strategy == "STRICT_SPREAD":
                # dev fallback: fewer nodes than workers — pack instead
                remove_placement_group(pg)
                pg = placement_group([bundle] * sc.num_workers,
                                     strategy="PACK")
                pg.ready(timeout=60.0)
            else:
                raise
        workers = []
        try:
            for rank in range(sc.num_workers):
                strat = PlacementGroupSchedulingStrategy(
                    placement_group=pg, placement_group_bundle_index=rank)
                opts = {"scheduling_strategy": strat,
                        "num_cpus": bundle.get("CPU", 1.0)}
                extra = {k: v for k, v in bundle.items() if k != "CPU"}
                if extra:
                    opts["resources"] = extra
                workers.append(_TrainWorker.options(**opts).remote(
                    rank, sc.num_workers, storage, name))
            return {"pg": pg, "workers": workers}
        except Exception:
            for w in workers:
                try:
                    kill(w)
                except Exception:
                    pass
            remove_placement_group(pg)
            raise

    def _teardown_gang(self, gang: dict) -> None:
        for w in gang.get("workers", ()):
            try:
                kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(gang["pg"])
        except Exception:
            pass


def _latest(history, latest_ckpt, last_metrics):
    """Rank-0's most recent report drives Result metrics/checkpoint."""
    for payload in reversed(history):
        if payload["rank"] == 0:
            last_metrics = payload["metrics"]
            if payload.get("checkpoint_path"):
                latest_ckpt = Checkpoint(payload["checkpoint_path"])
            break
    return latest_ckpt, last_metrics


def _drain(queue, exp_dir: str, saved: List[str],
           ckpt_config: CheckpointConfig,
           history: List[Dict[str, Any]]) -> None:
    """Pull all pending reports; persist rank-0 checkpoints into the
    experiment dir (checkpoint_000N) honoring num_to_keep."""
    from ..util.queue import Empty
    while True:
        try:
            payload = queue.get_nowait()
        except Empty:
            break
        history.append(payload)
        src = payload.get("checkpoint_path")
        if src and os.path.isdir(src):
            dst = os.path.join(exp_dir,
                               f"checkpoint_{len(saved):06d}")
            if os.path.exists(dst):
                shutil.rmtree(dst)
            shutil.copytree(src, dst)
            payload["checkpoint_path"] = dst
            saved.append(dst)
            keep = ckpt_config.num_to_keep
            if keep and len(saved) > keep:
                for old in saved[:-keep]:
                    shutil.rmtree(old, ignore_errors=True)
                del saved[:-keep]
