"""Trainer configuration dataclasses.

Reference: ``python/ray/air/config.py:94`` (ScalingConfig), ``:723``
(RunConfig), ``:523`` (FailureConfig), ``:574`` (CheckpointConfig). The
TPU-shaped addition: ``ScalingConfig.mesh`` — a `MeshSpec` describing
the global device mesh the worker gang assembles.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from ..parallel.mesh import MeshSpec


@dataclasses.dataclass
class ScalingConfig:
    """How many host workers, what resources each, what device mesh.

    num_workers: one per TPU host (4 chips/host on v5e); CPU-only
    training uses plain actors. ``use_tpu`` adds the TPU resource to each
    bundle so gang placement lands on TPU hosts.
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "STRICT_SPREAD"
    mesh: Optional[MeshSpec] = None

    def bundle(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu:
            res.setdefault("TPU", 4.0)     # chips per host
        return res


@dataclasses.dataclass
class FailureConfig:
    """max_failures: worker-gang restarts before giving up; -1 = infinite."""

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_frequency: int = 0


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    verbose: int = 0
