"""Checkpoint — directory-backed, with first-class JAX pytree support.

Reference: AIR ``Checkpoint`` (``air/checkpoint.py:67``) morphs between
dict/directory/URI. Here a checkpoint IS a directory (what the storage
layer and orbax want); dict convenience wraps it. JAX pytrees go through
**orbax** (async-capable, sharding-aware — the TPU-native answer to the
reference's torch.save path in ``train/torch/``).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
from typing import Any, Dict, Optional

_METRICS_FILE = ".rtpu_metrics.json"
_DICT_FILE = "data.pkl"
_PYTREE_DIR = "pytree"


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    # ------------------------------------------------------------- creation
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any],
                  dir: Optional[str] = None) -> "Checkpoint":
        path = dir or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, _DICT_FILE), "wb") as f:
            pickle.dump(data, f)
        return cls(path)

    @classmethod
    def from_pytree(cls, tree: Any, dir: Optional[str] = None,
                    extra: Optional[Dict[str, Any]] = None) -> "Checkpoint":
        """Save a JAX pytree (params / TrainState) via orbax."""
        path = dir or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        target = os.path.join(path, _PYTREE_DIR)
        if os.path.exists(target):
            shutil.rmtree(target)
        ckptr.save(target, tree)
        if extra:
            with open(os.path.join(path, _DICT_FILE), "wb") as f:
                pickle.dump(extra, f)
        return cls(path)

    # ------------------------------------------------------------- reading
    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None or os.path.abspath(path) == self.path:
            return self.path
        if os.path.exists(path):
            shutil.rmtree(path)
        shutil.copytree(self.path, path)
        return path

    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, _DICT_FILE), "rb") as f:
            return pickle.load(f)

    def to_pytree(self, template: Any = None) -> Any:
        """Restore a pytree; pass abstract arrays / shardings as
        ``template`` to restore sharded on-device (orbax restore_args)."""
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        target = os.path.join(self.path, _PYTREE_DIR)
        if template is None:
            return ckptr.restore(target)
        return ckptr.restore(target, item=template)

    # ------------------------------------------------------------ metadata
    def set_metrics(self, metrics: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, _METRICS_FILE), "w") as f:
            json.dump(metrics, f, default=str)

    def get_metrics(self) -> Dict[str, Any]:
        p = os.path.join(self.path, _METRICS_FILE)
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def __repr__(self):
        return f"Checkpoint({self.path})"
