"""Concurrency lint: AST + call-graph static analyzer for the runtime's
threading model, in the same table-driven spirit as ``check_metrics.py``.

Reference analogue: the TSan/deadlock-annotation coverage the C++ core
gets from sanitizer CI builds (PAPER.md §1 layers 0-1); a Python runtime
gets the equivalent from this pass plus the opt-in runtime sanitizer
(``_private/locksan.py``, ``RTPU_LOCKSAN=1``).

Rules (each has a golden-fixture test in tests/test_concurrency_lint.py):

(a) **Declared locks only.** Every ``threading.Lock/RLock/Condition``
    construction under ray_tpu/ must go through the ``locksan`` factory
    with a literal name that exists in ``locksan.REGISTRY`` AND in the
    DESIGN.md "Threading model & lock hierarchy" table; registry rows
    without a construction site are stale; names/modules/levels must
    agree across all three.

(b) **No lock-order inversion.** Per-function acquired-lock sets come
    from ``with <lock>:`` blocks; a call made while holding L
    contributes L -> M edges for every lock M the (transitively
    resolved) callee may acquire. Cycles in the acquisition-order graph
    and downhill edges (level(M) <= level(L)) are findings. Re-entry of
    a declared rlock is exempt; re-entry of a plain lock is a
    self-deadlock finding. (Explicit ``acquire()`` protocols — the
    transport's combining drainer — are covered at runtime by locksan,
    not here.)

(c) **No blocking calls under a lock.** Inside a ``with <lock>:`` body
    (lexically): ``Connection.send*/flush/kick``, request/reply RPCs,
    ``time.sleep``, socket ops, ``Future.result``/``join``, bare
    ``get()`` where the module imports the runtime's get, ``.remote()``
    submissions, ``subprocess.run``, and ``.wait()`` on anything other
    than the held lock's own condition. Escape hatch: a trailing
    ``# lint: allow-under-lock(<reason>)`` on the call line — counted
    and reported; an empty reason is a finding.

(d) **Reader-thread discipline.** Handlers reachable from the
    connection-reader dispatch tables (``NodeService._handle_direct``
    for ``_DIRECT_OPS``, ``CoreClient.handle_message``,
    ``RpcChannel._dispatch_one``, ``WorkerRuntime.run``) must not call
    functions marked ``# concurrency: dispatcher-only``, must not block
    (``result``/``join``/``sleep``), and must not make synchronous GCS
    RPCs (methods absent from ``RemoteControlPlane._CASTS``). Escape
    hatch: ``# lint: allow-on-reader(<reason>)`` on a call line stops
    traversal through that edge.

(e) **Protocol-op consistency.** Every op constant in ``protocol.py``
    needs at least one encoder (send site) and one handler (dispatch
    comparison), and every statically-visible payload tuple arity must
    agree across send sites and handler unpacks (the class of bug where
    an EXECUTE 4-tuple grows a field and one site is missed). Escape
    hatch: ``# lint: allow-op(<reason>)`` on the constant's line.

(f) **Config-knob registry.** Every ``_CONFIG_DEFS`` knob must have a
    README "Configuration" row whose env column is exactly
    ``RTPU_<NAME>``; stale/duplicate rows and ``CONFIG.<typo>`` reads
    of undefined knobs are findings.

(g) **Failpoint-site registry.** Every ``failpoints.fp(<site>)`` call
    must name a literal site registered in ``failpoints._SITES`` (a
    typo'd site silently never fires), and every registered site must
    have at least one planted call site (a stale row documents chaos
    coverage that doesn't exist).

(h) **Guarded-by field ownership** (``locksan.FIELDS`` — the data-side
    complement of the lock registry; reference: Clang ``GUARDED_BY``).
    Sub-checks: every declared guard is a REGISTRY lock (or a
    non-empty ``thread:``/``atomic:`` declaration); every declared
    field exists and its class carries ``@fieldsan.guarded`` (modules:
    a ``fieldsan.instrument_module`` call) so the runtime sanitizer
    actually sees it; every AST **write** to a lock-guarded field sits
    lexically under ``with <guard>`` — or inside a function annotated
    ``# concurrency: requires(<guard>)`` (Clang REQUIRES equivalent;
    call sites of such functions must themselves hold the guard) — or
    in ``__init__``, or carries a counted ``# lint: race-ok(<reason>)``
    waiver; the DESIGN.md "Shared-state ownership map" table mirrors
    FIELDS both directions; and an **inference pass** flags undeclared
    candidates — attributes assigned in ``__init__`` and mutated in
    functions reachable from two different thread entry points
    (reader roots + ``threading.Thread(target=...)`` functions,
    reusing rule (d)'s resolution) — so the registry can't rot as the
    code grows.

Wired into tier-1 (``tests/test_concurrency_lint.py``); standalone:
``python -m ray_tpu.scripts.check_concurrency`` (also via ``rtpu lint``).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

# ------------------------------------------------------------- constants

_LOCK_CTORS = ("Lock", "RLock", "Condition")
_FACTORY_FNS = ("lock", "rlock", "condition")

_WAIVER_UNDER_LOCK = re.compile(r"#\s*lint:\s*allow-under-lock\(([^)]*)\)")
_WAIVER_ON_READER = re.compile(r"#\s*lint:\s*allow-on-reader\(([^)]*)\)")
_WAIVER_OP = re.compile(r"#\s*lint:\s*allow-op\(([^)]*)\)")
_WAIVER_RACE_OK = re.compile(r"#\s*lint:\s*race-ok\(([^)]*)\)")
_DISPATCHER_ONLY = re.compile(r"#\s*concurrency:\s*dispatcher-only")
_REQUIRES = re.compile(r"#\s*concurrency:\s*requires\(([a-z0-9_.]+)\)")

# container methods that mutate their receiver (rule (h): a call
# ``self.<field>.append(...)`` is a write to <field>)
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "add", "pop", "popleft", "popitem",
    "remove", "discard", "update", "extend", "extendleft", "clear",
    "insert", "setdefault", "sort", "reverse", "rotate", "move_to_end",
    "difference_update", "intersection_update",
    "symmetric_difference_update",
})

# the guarded-by plane's target modules (ISSUE 15): FIELDS declarations
# and the undeclared-candidate inference are scoped to these stems
_FIELD_MODULES = ("node", "gcs", "client", "worker", "protocol",
                  "coll_transport", "telemetry", "scheduler",
                  "object_store", "history")

_OWNERSHIP_HEADING = "## Shared-state ownership map"

# Attribute-call names that block (or can block) the calling thread.
# ``wait`` is special-cased: allowed on the held lock's own condition.
_BLOCKING_ATTRS = frozenset({
    "send", "send_many", "sendall", "sendmsg", "recv", "recv_many",
    "recv_into", "connect", "accept", "flush", "kick",
    "request", "request_async", "_request", "_send", "result", "join",
    "remote", "sleep",
})
# blocking names when the receiver is the subprocess module
_SUBPROCESS_BLOCKING = frozenset({"run", "check_call", "check_output",
                                  "communicate"})
# receivers whose .flush()/.write() are console output, not transport
_CONSOLE_RECEIVERS = frozenset({"stdout", "stderr"})

# reader-thread roots: (file rel path, class, function). The dispatch
# tables these implement: node._DIRECT_OPS (answered inline on node
# reader threads), the worker main recv loop, the client reader loop's
# push handler, and RpcChannel's reply/push dispatch.
_READER_ROOTS = (
    ("_private/node.py", "NodeService", "_handle_direct"),
    ("_private/worker.py", "WorkerRuntime", "run"),
    ("_private/client.py", "CoreClient", "handle_message"),
    ("_private/rpc.py", "RpcChannel", "_dispatch_one"),
)

# blocking names on reader threads (sends are allowed there — replies
# leave on the arrival conn; parking the reader is what's forbidden)
_READER_BLOCKING = frozenset({"result", "join", "sleep"})

# attr names too generic to resolve by package-wide uniqueness (they
# collide with builtin container/executor methods)
_RESOLVE_DENYLIST = frozenset({
    "append", "add", "pop", "get", "put", "clear", "remove", "discard",
    "update", "extend", "close", "send", "items", "keys", "values",
    "join", "start", "result", "copy", "read", "write", "flush", "open",
    "acquire", "release", "sort", "count", "index", "insert", "popleft",
    "popitem", "setdefault", "submit", "wait", "run", "load", "loads",
    "dumps", "dump", "encode", "decode", "hex", "empty", "set", "kill",
    "poll", "cancel", "stop", "free", "name", "exists", "create",
})

_DESIGN_HEADING = "## Threading model & lock hierarchy"
_CONFIG_HEADING = "## Configuration"


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _walk_files(pkg_dir: str):
    """[(rel, tree, source_lines)] for every parseable .py under pkg."""
    out = []
    for dirpath, _dirs, files in os.walk(pkg_dir):
        if "__pycache__" in dirpath:
            continue
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            try:
                with open(path) as f:
                    src = f.read()
                tree = ast.parse(src, filename=path)
            except (SyntaxError, OSError):
                continue
            out.append((os.path.relpath(path, pkg_dir), tree,
                        src.splitlines()))
    return out


def _line(lines: List[str], lineno: int) -> str:
    return lines[lineno - 1] if 0 < lineno <= len(lines) else ""


# ===================================================== registry / rule (a)

def parse_locksan_registry(files) -> Dict[str, tuple]:
    """locksan.REGISTRY parsed from source (name -> (module, kind,
    level, protects)) — the analyzer never imports the runtime."""
    for rel, tree, _lines in files:
        if not rel.endswith("locksan.py"):
            continue
        for node in ast.walk(tree):
            tgt = val = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                tgt, val = node.target, node.value
            if (isinstance(tgt, ast.Name) and tgt.id == "REGISTRY"
                    and val is not None):
                try:
                    return ast.literal_eval(val)
                except (ValueError, SyntaxError):
                    return {}
    return {}


def parse_fields_registry(files) -> Dict[str, str]:
    """locksan.FIELDS parsed from source (field key -> guard spec) —
    like the lock registry, never imported."""
    for rel, tree, _lines in files:
        if not rel.endswith("locksan.py"):
            continue
        for node in ast.walk(tree):
            tgt = val = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                tgt, val = node.target, node.value
            if (isinstance(tgt, ast.Name) and tgt.id == "FIELDS"
                    and val is not None):
                try:
                    return ast.literal_eval(val)
                except (ValueError, SyntaxError):
                    return {}
    return {}


_DESIGN_ROW_RE = re.compile(
    r"^\|\s*`([a-z0-9_.]+)`\s*\|\s*`([^`]+)`\s*\|\s*(\d+)\s*\|"
    r"\s*(\w+)\s*\|", re.MULTILINE)

_OWNERSHIP_ROW_RE = re.compile(
    r"^\|\s*`([A-Za-z0-9_.]+)`\s*\|\s*`([^`]+)`\s*\|\s*([^|]*)\|",
    re.MULTILINE)


def parse_design_ownership_table(design_path: str) -> List[Tuple[str,
                                                                 str, str]]:
    """(field, guard, writers) rows of the DESIGN.md "Shared-state
    ownership map" table."""
    try:
        with open(design_path) as f:
            text = f.read()
    except OSError:
        return []
    start = text.find(_OWNERSHIP_HEADING)
    if start < 0:
        return []
    body = text[start + len(_OWNERSHIP_HEADING):]
    end = re.search(r"\n## ", body)
    if end:
        body = body[:end.start()]
    return [(f, g, w.strip()) for f, g, w in
            _OWNERSHIP_ROW_RE.findall(body)
            if f != "Field"]


def parse_design_lock_table(design_path: str) -> List[Tuple[str, str,
                                                            int, str]]:
    """(name, module, level, kind) rows of the DESIGN.md lock table."""
    try:
        with open(design_path) as f:
            text = f.read()
    except OSError:
        return []
    start = text.find(_DESIGN_HEADING)
    if start < 0:
        return []
    body = text[start + len(_DESIGN_HEADING):]
    end = re.search(r"\n## ", body)
    if end:
        body = body[:end.start()]
    return [(n, m, int(lv), k)
            for n, m, lv, k in _DESIGN_ROW_RE.findall(body)]


@dataclass
class LockSite:
    name: str
    rel: str
    lineno: int
    kind: str                       # lock | rlock | condition
    cv_lock_var: Optional[str]      # condition's shared-lock var name


def collect_lock_sites(files):
    """Returns (raw_sites, factory_sites, bindings).

    raw_sites: [(rel, lineno, ctor)] of direct threading constructions.
    factory_sites: [LockSite] of locksan factory calls.
    bindings: (rel, class_or_None, varname) -> lock name, for resolving
    ``with <expr>:`` items. ``self._x``/``cls._x`` resolve through the
    class key; module globals through the None key.
    """
    raw: List[tuple] = []
    sites: List[LockSite] = []
    bindings: Dict[tuple, str] = {}

    def scan(node, rel, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                scan(child, rel, child.name)
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan(child, rel, cls)
                continue
            for sub in ast.walk(child):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                if (isinstance(fn, ast.Attribute)
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id == "threading"
                        and fn.attr in _LOCK_CTORS
                        and not rel.endswith("locksan.py")):
                    raw.append((rel, sub.lineno, fn.attr))
                if (isinstance(fn, ast.Attribute)
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id == "locksan"
                        and fn.attr in _FACTORY_FNS):
                    name = (sub.args[0].value
                            if sub.args and isinstance(sub.args[0],
                                                       ast.Constant)
                            and isinstance(sub.args[0].value, str)
                            else None)
                    cv = None
                    if fn.attr == "condition" and len(sub.args) > 1 \
                            and isinstance(sub.args[1], ast.Name):
                        cv = sub.args[1].id
                    sites.append(LockSite(name or "<dynamic>", rel,
                                          sub.lineno, fn.attr, cv))
                    if name is None:
                        continue
                    # bind the assignment target, if this call is one
                    parent = child
                    for stmt in ast.walk(parent):
                        if (isinstance(stmt, ast.Assign)
                                and stmt.value is sub
                                and len(stmt.targets) == 1):
                            tgt = stmt.targets[0]
                            if isinstance(tgt, ast.Name):
                                # module-level Name assigns bind at
                                # (rel, None, var); class-body assigns
                                # at (rel, cls, var)
                                bindings[(rel, cls, tgt.id)] = name
                            elif (isinstance(tgt, ast.Attribute)
                                  and isinstance(tgt.value, ast.Name)
                                  and tgt.value.id in ("self", "cls")):
                                bindings[(rel, cls, tgt.attr)] = name
        return

    for rel, tree, _lines in files:
        scan(tree, rel, None)
    return raw, sites, bindings


# ==================================================== module/function model

@dataclass
class CallSite:
    lineno: int
    func_name: str                      # attr or bare name
    recv: Tuple[str, ...]               # receiver name chain, outermost last
    held: Tuple[str, ...]               # lock names held lexically
    callee: Optional[tuple] = None      # resolved (rel, cls, name)
    waived_under_lock: Optional[str] = None
    waived_on_reader: Optional[str] = None
    waived_race_ok: Optional[str] = None
    bare: bool = False                  # Name call (not attribute)


@dataclass
class FieldWrite:
    """One AST write to an attribute/global (rule (h))."""

    name: str                           # attr (self-scope) or global name
    lineno: int
    held: Tuple[str, ...]               # lock names held lexically
    scope: str                          # "self" | "global"
    waiver: Optional[str] = None        # race-ok reason (None = none)


@dataclass
class FuncInfo:
    key: tuple                          # (rel, cls_or_None, name)
    lineno: int
    n_params: Tuple[int, int] = (0, 0)  # (required, total) after self
    dispatcher_only: bool = False
    requires: Optional[str] = None      # declared caller-holds lock
    is_async: bool = False              # coroutine: a call site only
                                        # creates it, never runs it
    with_locks: List[tuple] = field(default_factory=list)
    # [(lockname, lineno, outer_held_names)]
    calls: List[CallSite] = field(default_factory=list)
    writes: List[FieldWrite] = field(default_factory=list)
    thread_targets: List[tuple] = field(default_factory=list)
    # [(recv_chain_or_name, lineno)] of threading.Thread(target=...)


def _recv_chain(node) -> Tuple[str, ...]:
    out = []
    cur = node
    while isinstance(cur, ast.Attribute):
        out.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        out.append(cur.id)
    return tuple(reversed(out))         # e.g. ("self", "gcs", "kv_get")


class _Analyzer:
    def __init__(self, repo_root: str):
        self.root = repo_root
        self.pkg = os.path.join(repo_root, "ray_tpu")
        self.files = _walk_files(self.pkg)
        self.lines = {rel: lines for rel, _t, lines in self.files}
        self.registry = parse_locksan_registry(self.files)
        self.fields = parse_fields_registry(self.files)
        (self.raw_sites, self.factory_sites,
         self.bindings) = collect_lock_sites(self.files)
        # rule (h) structural indexes
        self.guarded_classes: Set[tuple] = set()   # (rel, cls) decorated
        self.instrumented_mods: Set[str] = set()   # instrument_module args
        self.class_lines: Dict[tuple, int] = {}    # (rel, cls) -> lineno
        self.funcs: Dict[tuple, FuncInfo] = {}
        self.method_index: Dict[str, List[tuple]] = {}
        self.module_rels = {self._mod_of(rel): rel
                            for rel, _t, _l in self.files}
        self.aliases: Dict[str, Dict[str, str]] = {}  # rel -> alias -> rel
        self.from_funcs: Dict[str, Dict[str, tuple]] = {}
        self.imports_pkg_get: Set[str] = set()
        self.gcs_casts: Set[str] = set()
        self.waivers: List[tuple] = []   # (kind, rel, lineno, reason)
        self._index()

    # ------------------------------------------------------------- indexing
    @staticmethod
    def _mod_of(rel: str) -> str:
        mod = rel[:-3].replace(os.sep, ".")
        if mod.endswith(".__init__"):
            mod = mod[:-len(".__init__")]
        return mod

    def _resolve_module(self, rel: str, level: int,
                        module: Optional[str], name: str) -> Optional[str]:
        """rel path of the module an ImportFrom binds ``name`` to, or
        None when it binds a function/class instead of a module."""
        base = self._mod_of(rel).split(".")
        if rel.endswith("__init__.py"):
            base = base + ["__init__"]
        if level:
            base = base[:-level]
        parts = base + (module.split(".") if module else [])
        as_mod = ".".join(parts + [name])
        if as_mod in self.module_rels:
            return self.module_rels[as_mod]
        return None

    def _index(self):
        for rel, tree, lines in self.files:
            alias_map: Dict[str, str] = {}
            from_map: Dict[str, tuple] = {}
            for node in ast.walk(tree):
                if isinstance(node, ast.ImportFrom):
                    for a in node.names:
                        bound = a.asname or a.name
                        target = self._resolve_module(
                            rel, node.level, node.module, a.name)
                        if target is not None:
                            alias_map[bound] = target
                        else:
                            # from .mod import fn  /  from .. import get
                            src_mod = ".".join(
                                x for x in [self._parent_pkg(rel,
                                                             node.level),
                                            node.module] if x)
                            src_rel = self.module_rels.get(src_mod)
                            if src_rel is not None:
                                from_map[bound] = (src_rel, a.name)
                            if a.name == "get" and node.module is None:
                                self.imports_pkg_get.add(rel)
            self.aliases[rel] = alias_map
            self.from_funcs[rel] = from_map
            self._index_funcs(rel, tree, lines)
        # gcs cast methods (fire-and-forget: allowed on reader threads)
        for rel, tree, _lines in self.files:
            if not rel.endswith("gcs_service.py"):
                continue
            for node in ast.walk(tree):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == "_CASTS"):
                    for sub in ast.walk(node.value):
                        if (isinstance(sub, ast.Constant)
                                and isinstance(sub.value, str)):
                            self.gcs_casts.add(sub.value)

    def _parent_pkg(self, rel: str, level: int) -> str:
        base = self._mod_of(rel).split(".")
        if rel.endswith("__init__.py"):
            base = base + ["__init__"]
        return ".".join(base[:-level]) if level else ".".join(base[:-1])

    def _index_funcs(self, rel, tree, lines):
        # fieldsan structural evidence: decorated classes and
        # instrument_module(<globals>, "<mod>") calls in this file
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "instrument_module"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "fieldsan"
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)):
                self.instrumented_mods.add(node.args[1].value)

        def visit(node, cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    self.class_lines[(rel, child.name)] = child.lineno
                    for deco in child.decorator_list:
                        if ((isinstance(deco, ast.Attribute)
                             and deco.attr == "guarded"
                             and isinstance(deco.value, ast.Name)
                             and deco.value.id == "fieldsan")
                                or (isinstance(deco, ast.Name)
                                    and deco.id == "guarded")):
                            self.guarded_classes.add((rel, child.name))
                    visit(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    key = (rel, cls, child.name)
                    fi = FuncInfo(key=key, lineno=child.lineno,
                                  is_async=isinstance(
                                      child, ast.AsyncFunctionDef))
                    args = child.args
                    names = [a.arg for a in args.args]
                    if cls and names and names[0] in ("self", "cls"):
                        names = names[1:]
                    total = len(names)
                    fi.n_params = (total - len(args.defaults), total)
                    head = _line(lines, child.lineno)
                    above = _line(lines, child.lineno - 1)
                    deco_top = _line(lines, min(
                        (d.lineno for d in child.decorator_list),
                        default=child.lineno) - 1)
                    if (_DISPATCHER_ONLY.search(head)
                            or _DISPATCHER_ONLY.search(above)
                            or _DISPATCHER_ONLY.search(deco_top)):
                        fi.dispatcher_only = True
                    for src_line in (head, above, deco_top):
                        m = _REQUIRES.search(src_line)
                        if m:
                            fi.requires = m.group(1)
                            break
                    self._scan_body(fi, child, rel, cls, lines)
                    self.funcs[key] = fi
                    self.method_index.setdefault(child.name,
                                                 []).append(key)
        visit(tree, None)

    def _lock_of_expr(self, expr, rel, cls) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return (self.bindings.get((rel, None, expr.id))
                    or self.bindings.get((rel, cls, expr.id)))
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id in ("self", "cls")):
            hit = self.bindings.get((rel, cls, expr.attr))
            if hit is not None:
                return hit
            # fall back: unique attr binding anywhere in this file
            cands = {v for (r, _c, a), v in self.bindings.items()
                     if r == rel and a == expr.attr}
            if len(cands) == 1:
                return cands.pop()
        return None

    def _scan_body(self, fi: FuncInfo, func_node, rel, cls, lines):
        held: List[str] = []
        # names this function declares `global`: a whole-name rebind of
        # one of them is a module-field write (and, at runtime, would
        # replace a fieldsan proxy — rule (h) must see it)
        global_names: Set[str] = set()
        for sub in ast.walk(func_node):
            if isinstance(sub, ast.Global):
                global_names.update(sub.names)

        def note_write(target, lineno):
            """Record a store through ``target`` when it hits a
            ``self.<attr>`` / module-global field shape (rule (h))."""
            if isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    note_write(elt, lineno)
                return
            name = scope = None
            if isinstance(target, ast.Starred):
                target = target.value
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id in ("self", "cls")):
                name, scope = target.attr, "self"
            elif (isinstance(target, ast.Name)
                  and target.id in global_names):
                name, scope = target.id, "global"
            elif isinstance(target, ast.Subscript):
                base = target.value
                if (isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id in ("self", "cls")):
                    name, scope = base.attr, "self"
                elif isinstance(base, ast.Name):
                    name, scope = base.id, "global"
            if name is None:
                return
            src = _line(lines, lineno)
            m = _WAIVER_RACE_OK.search(src)
            fi.writes.append(FieldWrite(
                name=name, lineno=lineno, held=tuple(held), scope=scope,
                waiver=m.group(1).strip() if m else None))
            if m:
                self.waivers.append(("race-ok", rel, lineno,
                                     m.group(1).strip()))

        def walk(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return                      # separate scope/thread
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    note_write(tgt, node.lineno)
                for child in ast.iter_child_nodes(node):
                    walk(child)
                return
            if isinstance(node, ast.Delete):
                for tgt in node.targets:
                    note_write(tgt, node.lineno)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in node.items:
                    lock = self._lock_of_expr(item.context_expr, rel, cls)
                    if lock is None:
                        walk(item.context_expr)
                    else:
                        fi.with_locks.append((lock, item.context_expr
                                              .lineno, tuple(held)))
                        held.append(lock)
                        pushed += 1
                for stmt in node.body:
                    walk(stmt)
                for _ in range(pushed):
                    held.pop()
                return
            if isinstance(node, ast.Call):
                fn = node.func
                name = recv = None
                bare = False
                if isinstance(fn, ast.Attribute):
                    name = fn.attr
                    recv = _recv_chain(fn.value)
                elif isinstance(fn, ast.Name):
                    name = fn.id
                    recv = ()
                    bare = True
                if name is not None:
                    src = _line(lines, node.lineno)
                    m_u = _WAIVER_UNDER_LOCK.search(src)
                    m_r = _WAIVER_ON_READER.search(src)
                    m_k = _WAIVER_RACE_OK.search(src)
                    cs = CallSite(
                        lineno=node.lineno, func_name=name,
                        recv=recv or (), held=tuple(held), bare=bare,
                        waived_under_lock=(m_u.group(1).strip()
                                           if m_u else None),
                        waived_on_reader=(m_r.group(1).strip()
                                          if m_r else None),
                        waived_race_ok=(m_k.group(1).strip()
                                        if m_k else None))
                    fi.calls.append(cs)
                    if m_u:
                        self.waivers.append(("allow-under-lock", rel,
                                             node.lineno,
                                             cs.waived_under_lock))
                    if m_r:
                        self.waivers.append(("allow-on-reader", rel,
                                             node.lineno,
                                             cs.waived_on_reader))
                    if m_k:
                        self.waivers.append(("race-ok", rel,
                                             node.lineno,
                                             cs.waived_race_ok))
                    # container-mutator calls are writes to the field
                    if name in _MUTATOR_METHODS and recv:
                        if len(recv) == 2 and recv[0] in ("self", "cls"):
                            fi.writes.append(FieldWrite(
                                name=recv[1], lineno=node.lineno,
                                held=tuple(held), scope="self",
                                waiver=cs.waived_race_ok))
                        elif len(recv) == 1:
                            fi.writes.append(FieldWrite(
                                name=recv[0], lineno=node.lineno,
                                held=tuple(held), scope="global",
                                waiver=cs.waived_race_ok))
                    # thread entry points (rule (h) inference roots)
                    if name == "Thread":
                        for kw in node.keywords:
                            if kw.arg == "target":
                                fi.thread_targets.append(
                                    (_recv_chain(kw.value)
                                     if isinstance(kw.value,
                                                   (ast.Attribute,
                                                    ast.Name))
                                     else (), node.lineno))
            for child in ast.iter_child_nodes(node):
                walk(child)

        for stmt in func_node.body:
            walk(stmt)

    # ---------------------------------------------------------- resolution
    def resolve_call(self, rel: str, cls: Optional[str],
                     cs: CallSite) -> Optional[tuple]:
        if cs.bare:
            key = (rel, None, cs.func_name)
            if key in self.funcs:
                return key
            hit = self.from_funcs.get(rel, {}).get(cs.func_name)
            if hit is not None:
                key = (hit[0], None, hit[1])
                return key if key in self.funcs else None
            return None
        recv = cs.recv
        if recv and recv[0] in ("self", "cls") and len(recv) == 1:
            key = (rel, cls, cs.func_name)
            if key in self.funcs:
                return key
        if len(recv) == 1 and recv[0] in self.aliases.get(rel, {}):
            key = (self.aliases[rel][recv[0]], None, cs.func_name)
            return key if key in self.funcs else None
        # package-wide unique method name (skipping collision-prone ones)
        if cs.func_name in _RESOLVE_DENYLIST:
            return None
        cands = self.method_index.get(cs.func_name, ())
        if len(cands) == 1:
            return cands[0]
        return None

    def resolve_all(self) -> None:
        for (rel, cls, _name), fi in self.funcs.items():
            for cs in fi.calls:
                cs.callee = self.resolve_call(rel, cls, cs)

    # ------------------------------------------------------- rule (b) graph
    def may_acquire(self) -> Dict[tuple, Set[str]]:
        may = {k: {w[0] for w in fi.with_locks}
               for k, fi in self.funcs.items()}
        changed = True
        while changed:
            changed = False
            for k, fi in self.funcs.items():
                cur = may[k]
                for cs in fi.calls:
                    if cs.callee is None or cs.waived_under_lock:
                        continue
                    callee_fi = self.funcs.get(cs.callee)
                    if callee_fi is not None and callee_fi.is_async:
                        continue    # a call only creates the coroutine
                    extra = may.get(cs.callee, ())
                    if not cur.issuperset(extra):
                        cur.update(extra)
                        changed = True
        return may

    def order_edges(self, may) -> Dict[tuple, tuple]:
        """(held_lock, acquired_lock) -> example (rel, lineno, via)."""
        edges: Dict[tuple, tuple] = {}
        for (rel, _cls, _name), fi in self.funcs.items():
            for lock, lineno, outer in fi.with_locks:
                for h in outer:
                    edges.setdefault((h, lock), (rel, lineno, None))
            for cs in fi.calls:
                if (cs.callee is None or not cs.held
                        or cs.waived_under_lock):
                    continue
                for m in may.get(cs.callee, ()):
                    for h in cs.held:
                        edges.setdefault(
                            (h, m), (rel, cs.lineno,
                                     "via %s" % (cs.callee[2],)))
        return edges


# ============================================================ rule checks

def _check_registry(an: _Analyzer, design_path: str) -> List[str]:
    problems: List[str] = []
    reg = an.registry
    if not reg:
        problems.append("locksan.REGISTRY not found/parseable — the "
                        "lock-registry scanner is broken")
        return problems
    for rel, lineno, ctor in an.raw_sites:
        problems.append(
            f"{rel}:{lineno}: raw threading.{ctor}() construction — "
            "runtime locks must go through locksan.lock/rlock/"
            "condition(<declared name>)")
    by_name: Dict[str, List[LockSite]] = {}
    for s in an.factory_sites:
        by_name.setdefault(s.name, []).append(s)
    for name, sites in sorted(by_name.items()):
        if name == "<dynamic>":
            for s in sites:
                problems.append(
                    f"{s.rel}:{s.lineno}: locksan factory called with a "
                    "non-literal name — the registry lint can't see it")
            continue
        if name not in reg:
            for s in sites:
                problems.append(
                    f"{s.rel}:{s.lineno}: lock name {name!r} is not "
                    "declared in locksan.REGISTRY")
            continue
        mod, kind, _level = reg[name][0], reg[name][1], reg[name][2]
        for s in sites:
            if s.rel.replace(os.sep, "/") != mod:
                problems.append(
                    f"{s.rel}:{s.lineno}: lock {name!r} declared for "
                    f"module {mod} but constructed here")
        kinds = {s.kind for s in sites}
        if len(sites) > 1:
            # one lock + one condition sharing it is the only legal
            # duplicate (the condition names the same registry entry)
            cond = [s for s in sites if s.kind == "condition"]
            lk = [s for s in sites if s.kind != "condition"]
            ok = (len(cond) == 1 and len(lk) == 1
                  and cond[0].cv_lock_var is not None)
            if not ok:
                problems.append(
                    f"lock name {name!r}: constructed at "
                    f"{len(sites)} sites — one construction site per "
                    "declared lock (condition-over-lock pairs exempt)")
        site_kind = ("condition" if "condition" in kinds
                     else sites[0].kind)
        if site_kind != kind:
            problems.append(
                f"lock {name!r}: registry declares kind {kind} but the "
                f"construction site uses {site_kind}")
    for name in sorted(set(reg) - set(by_name)):
        problems.append(
            f"lock {name!r}: declared in locksan.REGISTRY but never "
            "constructed — stale registry row")
    # levels must be unique (the hierarchy is a total order)
    seen_lv: Dict[int, str] = {}
    for name, row in sorted(reg.items()):
        lv = row[2]
        if lv in seen_lv:
            problems.append(
                f"locks {seen_lv[lv]!r} and {name!r} share level {lv} — "
                "levels must be distinct (the hierarchy is total)")
        else:
            seen_lv[lv] = name
    # DESIGN.md table must mirror the registry
    rows = parse_design_lock_table(design_path)
    if not rows:
        problems.append(
            "DESIGN.md has no 'Threading model & lock hierarchy' table "
            "— the declared hierarchy must be documented")
        return problems
    doc = {n: (m, lv, k) for n, m, lv, k in rows}
    if len(doc) != len(rows):
        problems.append("DESIGN.md lock table has duplicate rows")
    for name, row in sorted(reg.items()):
        d = doc.get(name)
        if d is None:
            problems.append(
                f"lock {name!r}: in locksan.REGISTRY but missing from "
                "the DESIGN.md lock-hierarchy table")
        elif (d[0], d[1], d[2]) != (row[0], row[2], row[1]):
            problems.append(
                f"lock {name!r}: DESIGN.md row (module={d[0]}, "
                f"level={d[1]}, kind={d[2]}) disagrees with "
                f"locksan.REGISTRY (module={row[0]}, level={row[2]}, "
                f"kind={row[1]})")
    for name in sorted(set(doc) - set(reg)):
        problems.append(
            f"lock {name!r}: documented in DESIGN.md but absent from "
            "locksan.REGISTRY — stale doc row")
    return problems


def _check_order(an: _Analyzer) -> List[str]:
    problems: List[str] = []
    reg = an.registry
    may = an.may_acquire()
    edges = an.order_edges(may)
    kind_of = {n: row[1] for n, row in reg.items()}
    level_of = {n: row[2] for n, row in reg.items()}
    adj: Dict[str, Set[str]] = {}
    for (a, b), (rel, lineno, via) in sorted(edges.items()):
        if a == b:
            if kind_of.get(a) != "rlock":
                problems.append(
                    f"{rel}:{lineno}: lock {a!r} re-acquired while held "
                    f"({via or 'nested with'}) — it is not an rlock: "
                    "guaranteed self-deadlock")
            continue
        adj.setdefault(a, set()).add(b)
        la, lb = level_of.get(a), level_of.get(b)
        if la is not None and lb is not None and lb <= la:
            problems.append(
                f"{rel}:{lineno}: acquires {b!r} (level {lb}) while "
                f"holding {a!r} (level {la}){' ' + via if via else ''} "
                "— violates the declared strictly-increasing hierarchy")
    # cycle scan (covers edges among unregistered/test locks too)
    state: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        state[n] = 1
        stack.append(n)
        for m in sorted(adj.get(n, ())):
            if state.get(m, 0) == 1:
                return stack[stack.index(m):] + [m]
            if state.get(m, 0) == 0:
                cyc = dfs(m)
                if cyc:
                    return cyc
        stack.pop()
        state[n] = 2
        return None

    for n in sorted(adj):
        if state.get(n, 0) == 0:
            cyc = dfs(n)
            if cyc:
                problems.append(
                    "lock-order cycle: " + " -> ".join(cyc)
                    + " — deadlock-capable inversion")
                break
    return problems


def _is_blocking_call(an: _Analyzer, rel: str, cs: CallSite,
                      cond_ok: bool) -> Optional[str]:
    """Reason string if this call blocks, else None. ``cond_ok``: a
    ``wait`` on the held lock's own condition variable is legal."""
    name = cs.func_name
    recv_last = cs.recv[-1] if cs.recv else ""
    if recv_last in _CONSOLE_RECEIVERS:
        return None
    if cs.bare:
        if name == "get" and rel in an.imports_pkg_get:
            return "blocking runtime get()"
        return None
    if name == "wait":
        if cond_ok:
            return None
        return ".wait() on a condition/event other than the held " \
               "lock's own"
    if name in _BLOCKING_ATTRS:
        if name == "sleep" and cs.recv and cs.recv[0] != "time":
            return None
        if name == "join" and not _is_thread_join(cs):
            return None
        return f"blocking .{name}()"
    if cs.recv and cs.recv[0] == "subprocess" \
            and name in _SUBPROCESS_BLOCKING:
        return f"subprocess.{name}() under a lock"
    if len(cs.recv) >= 2 and cs.recv[-1] in ("gcs", "_gcs", "plane") \
            and name not in an.gcs_casts:
        return f"synchronous GCS RPC .{name}() (not in _CASTS)"
    return None


def _is_thread_join(cs: CallSite) -> bool:
    """``.join()`` blocks only on threads/processes; ``os.path.join``
    and ``str.join`` (the overwhelming uses) are pure. Judge by the
    receiver name."""
    if not cs.recv:
        return False                    # "".join / f-string receivers
    last = cs.recv[-1]
    if last == "path":
        return False                    # os.path.join
    return (last in ("t", "th", "thread", "proc", "process", "worker")
            or last.endswith("thread") or last.endswith("proc"))


def _check_blocking_under_lock(an: _Analyzer) -> List[str]:
    problems: List[str] = []
    for (rel, cls, _name), fi in sorted(
            an.funcs.items(), key=lambda kv: (kv[0][0], kv[0][1] or "",
                                              kv[0][2])):
        # condition names aliased to held locks: wait on the held
        # lock's own condition is the condvar protocol, not a foreign
        # blocking wait
        for cs in fi.calls:
            if not cs.held:
                continue
            if cs.waived_under_lock is not None:
                if not cs.waived_under_lock:
                    problems.append(
                        f"{rel}:{cs.lineno}: allow-under-lock waiver "
                        "with an empty reason")
                continue
            cond_ok = False
            if cs.func_name == "wait" and cs.recv:
                wait_lock = an._lock_of_expr(
                    ast.Name(id=cs.recv[-1]), rel, cls) \
                    if len(cs.recv) == 1 else None
                if len(cs.recv) == 2 and cs.recv[0] in ("self", "cls"):
                    wait_lock = an.bindings.get((rel, cls, cs.recv[1]))
                cond_ok = wait_lock is not None and wait_lock in cs.held
            reason = _is_blocking_call(an, rel, cs, cond_ok)
            if reason:
                problems.append(
                    f"{rel}:{cs.lineno}: {reason} while holding "
                    f"{'/'.join(cs.held)!s} — move it outside the lock "
                    "or waive with # lint: allow-under-lock(reason)")
    return problems


def _check_reader_discipline(an: _Analyzer) -> List[str]:
    problems: List[str] = []
    roots = []
    for rel, cls, name in _READER_ROOTS:
        key = (rel.replace("/", os.sep), cls, name)
        if key in an.funcs:
            roots.append(key)
        else:
            problems.append(
                f"reader root {cls}.{name} not found in {rel} — the "
                "reader-discipline scanner is broken")
    seen: Dict[tuple, tuple] = {}
    frontier = [(r, (r,)) for r in roots]
    while frontier:
        key, path = frontier.pop()
        fi = an.funcs.get(key)
        if fi is None:
            continue
        for cs in fi.calls:
            if cs.waived_on_reader is not None:
                if not cs.waived_on_reader:
                    problems.append(
                        f"{key[0]}:{cs.lineno}: allow-on-reader waiver "
                        "with an empty reason")
                continue
            pretty = " -> ".join(k[2] for k in path)
            if cs.callee is not None:
                callee_fi = an.funcs.get(cs.callee)
                if callee_fi is not None and callee_fi.is_async:
                    continue    # runs on the asyncio loop, not here
                if callee_fi is not None and callee_fi.dispatcher_only:
                    problems.append(
                        f"{key[0]}:{cs.lineno}: reader-thread path "
                        f"[{pretty}] calls dispatcher-only function "
                        f"{cs.callee[2]!r}")
                    continue
                if cs.callee not in seen:
                    seen[cs.callee] = path
                    frontier.append((cs.callee, path + (cs.callee,)))
            name = cs.func_name
            if (name in _READER_BLOCKING
                    and not (name == "sleep" and cs.recv
                             and cs.recv[0] != "time")
                    and not (name == "join"
                             and not _is_thread_join(cs))):
                problems.append(
                    f"{key[0]}:{cs.lineno}: reader-thread path "
                    f"[{pretty}] blocks in .{name}() — reader threads "
                    "must never park (waive with "
                    "# lint: allow-on-reader(reason))")
            if (len(cs.recv) >= 2
                    and cs.recv[-1] in ("gcs", "_gcs")
                    and name not in an.gcs_casts
                    and name not in _RESOLVE_DENYLIST):
                problems.append(
                    f"{key[0]}:{cs.lineno}: reader-thread path "
                    f"[{pretty}] makes a synchronous GCS RPC "
                    f".{name}() (not in RemoteControlPlane._CASTS)")
    return problems


# ======================================================== rule (e): protocol

def _collect_protocol_ops(files) -> Dict[str, tuple]:
    """op name -> (value, lineno, waiver_reason_or_None)."""
    out: Dict[str, tuple] = {}
    for rel, tree, lines in files:
        if not rel.endswith("_private/protocol.py".replace("/", os.sep)) \
                and not rel.endswith("protocol.py"):
            continue
        for node in tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, int)):
                name = node.targets[0].id
                if (not name.isupper() or name.startswith("_")
                        or name.startswith("KIND_")):
                    continue
                src = _line(lines, node.lineno)
                m = _WAIVER_OP.search(src)
                out[name] = (node.value.value, node.lineno,
                             m.group(1).strip() if m else None)
        break
    return out


_SEND_FUNCS = frozenset({"send", "send_many", "send_lazy", "_send",
                         "_reply", "_reply_batched", "request",
                         "request_async", "_request", "_debug_fanout",
                         "_send_submission", "cast", "_cast"})


def _op_ref_name(node, op_names: Set[str], in_protocol: bool
                 ) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and node.attr in op_names
            and isinstance(node.value, ast.Name)):
        return node.attr
    if in_protocol and isinstance(node, ast.Name) and node.id in op_names:
        return node.id
    return None


def _payload_arity(node) -> Optional[int]:
    if isinstance(node, ast.Tuple):
        return len(node.elts)
    if isinstance(node, ast.Lambda) and isinstance(node.body, ast.Tuple):
        return len(node.body.elts)
    return None


def check_protocol_ops(files, funcs: Dict[tuple, FuncInfo]) -> List[str]:
    ops = _collect_protocol_ops(files)
    if not ops:
        return ["no op constants found in protocol.py — the protocol "
                "scanner is broken"]
    op_names = set(ops)
    enc_arity: Dict[str, List[tuple]] = {n: [] for n in op_names}
    enc_any: Dict[str, List[tuple]] = {n: [] for n in op_names}
    handler: Dict[str, List[tuple]] = {n: [] for n in op_names}
    hnd_arity: Dict[str, List[tuple]] = {n: [] for n in op_names}

    # function param table for starred-call handler arities
    params: Dict[tuple, Tuple[int, int]] = {
        k: fi.n_params for k, fi in funcs.items()}
    by_name: Dict[str, List[tuple]] = {}
    for k in funcs:
        by_name.setdefault(k[2], []).append(k)

    for rel, tree, _lines in files:
        in_proto = rel.endswith("protocol.py")
        # Each op reference is classified exactly once, by priority:
        # handler context (inside any Compare / all-op container) >
        # strong encoder ((OP, payload) 2-tuple or send-func arg) >
        # weak encoder (any other read). Definition targets in
        # protocol.py are excluded entirely.
        claimed: Set[int] = set()

        def refs_in(node) -> List[tuple]:
            out = []
            for sub in ast.walk(node):
                r = _op_ref_name(sub, op_names, in_proto)
                if r is not None:
                    out.append((id(sub), r, sub.lineno))
            return out

        if in_proto:
            for node in tree.body:
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        for nid, _r, _ln in refs_in(tgt):
                            claimed.add(nid)
        # pass 1: handler contexts
        for node in ast.walk(tree):
            if isinstance(node, ast.Compare):
                for nid, r, ln in refs_in(node):
                    if nid not in claimed:
                        claimed.add(nid)
                        handler[r].append((rel, ln))
            elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
                elts = getattr(node, "elts", [])
                refs = [_op_ref_name(e, op_names, in_proto)
                        for e in elts]
                if elts and all(refs) and (len(elts) > 1
                                           or isinstance(node,
                                                         (ast.Set,))
                                           or len(elts) == 1):
                    # container whose members are ALL ops: a dispatch/
                    # membership/reply-ops table -> handler evidence
                    # (a 2-tuple (OP, payload) never matches: payload
                    # is not an op ref)
                    for e, r in zip(elts, refs):
                        if id(e) not in claimed:
                            claimed.add(id(e))
                            handler[r].append((rel, e.lineno))
        # pass 2: handler unpack arities (per op-comparing If branch)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.If)
                    and isinstance(node.test, ast.Compare)):
                continue
            refs = [r for _nid, r, _ln in refs_in(node.test)]
            if not refs:
                continue
            arity = None
            for sub in node.body:
                for s in ast.walk(sub):
                    if (isinstance(s, ast.Assign)
                            and len(s.targets) == 1
                            and isinstance(s.targets[0], ast.Tuple)
                            and isinstance(s.value, ast.Name)):
                        n = len(s.targets[0].elts)
                        arity = (n, n)
                        break
                    if (isinstance(s, ast.Call) and any(
                            isinstance(a, ast.Starred)
                            for a in s.args)):
                        fn = s.func
                        fname = (fn.attr if isinstance(
                            fn, ast.Attribute) else
                            fn.id if isinstance(fn, ast.Name)
                            else None)
                        cands = by_name.get(fname or "", ())
                        if len(cands) == 1:
                            req, tot = params[cands[0]]
                            bound = sum(
                                1 for a in s.args
                                if not isinstance(a, ast.Starred))
                            arity = (max(0, req - bound), tot - bound)
                            break
                if arity:
                    break
            if arity:
                for r in refs:
                    hnd_arity[r].append((rel, node.lineno, arity))
        # pass 3: encoder contexts
        for node in ast.walk(tree):
            if isinstance(node, ast.Tuple) and len(node.elts) == 2:
                r0 = _op_ref_name(node.elts[0], op_names, in_proto)
                r1 = _op_ref_name(node.elts[1], op_names, in_proto)
                if r0 and not r1 and id(node.elts[0]) not in claimed:
                    claimed.add(id(node.elts[0]))
                    enc_any[r0].append((rel, node.lineno))
                    ar = _payload_arity(node.elts[1])
                    if ar is not None:
                        enc_arity[r0].append((rel, node.lineno, ar))
            elif isinstance(node, ast.Call):
                fn = node.func
                fname = (fn.attr if isinstance(fn, ast.Attribute)
                         else fn.id if isinstance(fn, ast.Name)
                         else None)
                if fname not in _SEND_FUNCS:
                    continue
                for i, a in enumerate(node.args):
                    r = _op_ref_name(a, op_names, in_proto)
                    if r and id(a) not in claimed:
                        claimed.add(id(a))
                        enc_any[r].append((rel, node.lineno))
                        if i + 1 < len(node.args):
                            ar = _payload_arity(node.args[i + 1])
                            if ar is not None:
                                enc_arity[r].append(
                                    (rel, node.lineno, ar))
                        break
        # pass 4: weak encoder evidence (anything unclaimed: an op
        # flowing through a variable/property into a send)
        for node in ast.walk(tree):
            r = _op_ref_name(node, op_names, in_proto)
            if r is not None and id(node) not in claimed:
                claimed.add(id(node))
                enc_any[r].append((rel, node.lineno))

    problems: List[str] = []
    for name in sorted(op_names):
        _value, lineno, waiver = ops[name]
        if waiver is not None:
            if not waiver:
                problems.append(
                    f"protocol.py:{lineno}: allow-op waiver on {name} "
                    "with an empty reason")
            continue
        if not handler[name] and not enc_any[name]:
            problems.append(
                f"protocol op {name}: dead — never sent and never "
                "handled (retire the constant or waive with "
                "# lint: allow-op(reason))")
            continue
        if not handler[name]:
            problems.append(
                f"protocol op {name}: no handler — nothing compares "
                "against it in any dispatch path")
        if not enc_any[name]:
            problems.append(
                f"protocol op {name}: handled but never sent — no "
                "encoder site constructs a frame with it")
        arities = {a for _r, _l, a in enc_arity[name]}
        if len(arities) > 1:
            sites = ", ".join(f"{r}:{ln}(arity {a})"
                              for r, ln, a in enc_arity[name])
            problems.append(
                f"protocol op {name}: send sites disagree on payload "
                f"tuple arity: {sites}")
        elif len(arities) == 1:
            (enc_n,) = arities
            for r, ln, (lo, hi) in hnd_arity[name]:
                if not (lo <= enc_n <= hi):
                    problems.append(
                        f"protocol op {name}: send sites use a "
                        f"{enc_n}-tuple payload but the handler at "
                        f"{r}:{ln} unpacks {lo}"
                        + (f"..{hi}" if hi != lo else "")
                        + " fields")
    return problems


# ========================================================= rule (f): config

def _config_knobs(files) -> Dict[str, int]:
    for rel, tree, _lines in files:
        if not rel.endswith("config.py"):
            continue
        for node in ast.walk(tree):
            tgt = val = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                tgt, val = node.target, node.value
            if (isinstance(tgt, ast.Name) and tgt.id == "_CONFIG_DEFS"
                    and isinstance(val, ast.Dict)):
                out = {}
                for k in val.keys:
                    if isinstance(k, ast.Constant) and isinstance(
                            k.value, str):
                        out[k.value] = k.lineno
                return out
    return {}


_CONFIG_ROW_RE = re.compile(
    r"^\|\s*`([a-z0-9_]+)`\s*\|\s*`(RTPU_[A-Z0-9_]+)`\s*\|",
    re.MULTILINE)


def check_config_registry(files, readme_path: str) -> List[str]:
    problems: List[str] = []
    knobs = _config_knobs(files)
    if not knobs:
        return ["no _CONFIG_DEFS found in config.py — the config "
                "scanner is broken"]
    try:
        with open(readme_path) as f:
            text = f.read()
    except OSError:
        text = ""
    start = text.find(_CONFIG_HEADING)
    if start < 0:
        return ["README.md has no '## Configuration' section — every "
                "CONFIG knob must be documented there"]
    body = text[start + len(_CONFIG_HEADING):]
    end = re.search(r"\n## ", body)
    if end:
        body = body[:end.start()]
    rows = _CONFIG_ROW_RE.findall(body)
    seen: Set[str] = set()
    for knob, env in rows:
        if knob in seen:
            problems.append(
                f"config knob {knob!r}: duplicate README row")
        seen.add(knob)
        want = "RTPU_" + knob.upper()
        if env != want:
            problems.append(
                f"config knob {knob!r}: README env column says {env} "
                f"but the override is {want}")
        if knob not in knobs:
            problems.append(
                f"config knob {knob!r}: README row has no matching "
                "_CONFIG_DEFS entry — stale doc row")
    for knob in sorted(set(knobs) - seen):
        problems.append(
            f"config knob {knob!r} (config.py:{knobs[knob]}): not "
            "documented in the README 'Configuration' table")
    # CONFIG.<attr> reads must name real knobs (typo'd reads silently
    # AttributeError only when hit at runtime)
    meth = {"dump", "reload"}
    for rel, tree, _lines in files:
        for node in ast.walk(tree):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "CONFIG"
                    and node.attr not in knobs
                    and node.attr not in meth
                    and not node.attr.startswith("_")):
                problems.append(
                    f"{rel}:{node.lineno}: CONFIG.{node.attr} is not a "
                    "defined knob in _CONFIG_DEFS")
    return problems


# ===================================================== rule (g): failpoints

def check_failpoint_registry(files) -> List[str]:
    """Failpoint sites are registry-linted like config knobs: fp() call
    sites and failpoints._SITES must agree both directions."""
    problems: List[str] = []
    sites: Optional[tuple] = None
    for rel, tree, _lines in files:
        if not rel.endswith("failpoints.py"):
            continue
        for node in ast.walk(tree):
            tgt = val = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                tgt, val = node.target, node.value
            if (isinstance(tgt, ast.Name) and tgt.id == "_SITES"
                    and val is not None):
                try:
                    sites = tuple(ast.literal_eval(val))
                except (ValueError, SyntaxError):
                    sites = None
        break
    if sites is None:
        return ["no _SITES tuple found in failpoints.py — the "
                "failpoint-registry scanner is broken"]
    planted: Dict[str, List[tuple]] = {}
    for rel, tree, _lines in files:
        if rel.endswith("failpoints.py"):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr == "fp"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "failpoints"):
                continue
            if (not node.args
                    or not isinstance(node.args[0], ast.Constant)
                    or not isinstance(node.args[0].value, str)):
                problems.append(
                    f"{rel}:{node.lineno}: failpoints.fp() called with "
                    "a non-literal site — the registry lint can't see "
                    "it")
                continue
            site = node.args[0].value
            planted.setdefault(site, []).append((rel, node.lineno))
            if site not in sites:
                problems.append(
                    f"{rel}:{node.lineno}: failpoint site {site!r} is "
                    "not registered in failpoints._SITES")
    for site in sorted(set(sites) - set(planted)):
        problems.append(
            f"failpoint site {site!r}: registered in "
            "failpoints._SITES but never planted (no "
            "failpoints.fp() call site) — stale registry row")
    return problems


# ================================================= rule (h): guarded fields

def _stem_rels(an: _Analyzer) -> Dict[str, str]:
    """module short name -> rel path, preferring _private/<stem>.py."""
    out: Dict[str, str] = {}
    for rel, _t, _l in an.files:
        stem = os.path.basename(rel)[:-3]
        posix = rel.replace(os.sep, "/")
        if posix == f"_private/{stem}.py" or stem not in out:
            if posix == f"_private/{stem}.py" or f"/{stem}.py" not in \
                    out.get(stem, "").replace(os.sep, "/"):
                out.setdefault(stem, rel)
        if posix == f"_private/{stem}.py":
            out[stem] = rel
    return out


def _parse_guard(spec: str) -> Tuple[str, str]:
    """(kind, payload): ("thread", pat) | ("atomic", reason) |
    ("lock", name) | ("static-lock", name). static-lock fields carry
    full rule-(h) write verification but are exempt from runtime
    instrumentation (the documented hot-path form, ``"<lock>|static"``)."""
    if spec.startswith("thread:"):
        return "thread", spec[len("thread:"):].strip()
    if spec.startswith("atomic:"):
        return "atomic", spec[len("atomic:"):].strip()
    if spec.endswith("|static"):
        return "static-lock", spec[:-len("|static")]
    return "lock", spec


def _module_level_names(tree) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        tgts = []
        if isinstance(node, ast.Assign):
            tgts = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            tgts = [node.target]
        for t in tgts:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


def _check_fields(an: _Analyzer, design_path: str) -> List[str]:
    problems: List[str] = []
    fields = an.fields
    if not fields:
        return ["locksan.FIELDS not found/parseable — the guarded-by "
                "field scanner is broken"]
    reg = an.registry
    stem_rel = _stem_rels(an)
    trees = {rel: tree for rel, tree, _l in an.files}

    declared_self: Dict[tuple, Dict[str, tuple]] = {}
    declared_glob: Dict[str, Dict[str, tuple]] = {}
    for key, spec in sorted(fields.items()):
        parts = key.split(".")
        kind, payload = _parse_guard(spec)
        if kind in ("thread", "atomic") and not payload:
            problems.append(
                f"field {key}: {kind}: declaration with an empty "
                f"{'pattern' if kind == 'thread' else 'reason'}")
        if kind in ("lock", "static-lock") and payload not in reg:
            problems.append(
                f"field {key}: guard {payload!r} is not a declared "
                "lock in locksan.REGISTRY")
        if len(parts) == 3:
            rel = stem_rel.get(parts[0])
            if rel is None:
                problems.append(
                    f"field {key}: module {parts[0]!r} not found under "
                    "ray_tpu/ — stale registry row")
                continue
            declared_self.setdefault((rel, parts[1]), {})[parts[2]] = \
                (key, spec, kind)
        elif len(parts) == 2:
            rel = stem_rel.get(parts[0])
            if rel is None:
                problems.append(
                    f"field {key}: module {parts[0]!r} not found under "
                    "ray_tpu/ — stale registry row")
                continue
            declared_glob.setdefault(rel, {})[parts[1]] = \
                (key, spec, kind)
        else:
            problems.append(
                f"field {key}: key must be <module>.<Class>.<attr> or "
                "<module>.<name>")

    # existence + instrumentation evidence
    written_attrs: Dict[tuple, Set[str]] = {}
    for (rel, cls, _name), fi in an.funcs.items():
        if cls is None:
            continue
        s = written_attrs.setdefault((rel, cls), set())
        for w in fi.writes:
            if w.scope == "self":
                s.add(w.name)
    for (rel, cls), attrs in sorted(declared_self.items()):
        if (rel, cls) not in an.class_lines:
            for attr, (key, _spec, _kind) in sorted(attrs.items()):
                problems.append(
                    f"field {key}: class {cls} not found in {rel} — "
                    "stale registry row")
            continue
        have = written_attrs.get((rel, cls), set())
        for attr, (key, _spec, _kind) in sorted(attrs.items()):
            if attr not in have:
                problems.append(
                    f"field {key}: attribute never assigned in {cls} "
                    "— stale registry row")
        if (any(k not in ("atomic", "static-lock")
                for _key, _s, k in attrs.values())
                and (rel, cls) not in an.guarded_classes):
            problems.append(
                f"{rel}:{an.class_lines[(rel, cls)]}: class {cls} "
                "declares guarded fields but lacks @fieldsan.guarded — "
                "the runtime sanitizer cannot instrument them")
    for rel, names in sorted(declared_glob.items()):
        stem = os.path.basename(rel)[:-3]
        mod_names = _module_level_names(trees[rel])
        for name, (key, _spec, kind) in sorted(names.items()):
            if name not in mod_names:
                problems.append(
                    f"field {key}: module-level name never assigned in "
                    f"{rel} — stale registry row")
        if (any(k not in ("atomic", "static-lock")
                for _key, _s, k in names.values())
                and stem not in an.instrumented_mods):
            problems.append(
                f"{rel}: declares module-level guarded fields but "
                f"never calls fieldsan.instrument_module(globals(), "
                f"{stem!r}) — the runtime sanitizer cannot see them")

    # every write to a lock-guarded field sits under its guard
    for (rel, cls, fname), fi in sorted(
            an.funcs.items(), key=lambda kv: (kv[0][0], kv[0][1] or "",
                                              kv[0][2])):
        if fi.requires and fi.requires not in reg:
            problems.append(
                f"{rel}:{fi.lineno}: {fname} requires({fi.requires}) "
                "names an undeclared lock")
        self_decl = declared_self.get((rel, cls), {}) if cls else {}
        glob_decl = declared_glob.get(rel, {})
        for w in fi.writes:
            decl = (self_decl.get(w.name) if w.scope == "self"
                    else glob_decl.get(w.name))
            if decl is None:
                continue
            key, spec, kind = decl
            if kind not in ("lock", "static-lock"):
                continue
            gname = (spec[:-len("|static")] if kind == "static-lock"
                     else spec)
            if fname == "__init__" and w.scope == "self":
                continue        # single-threaded construction window
            if w.waiver is not None:
                if not w.waiver:
                    problems.append(
                        f"{rel}:{w.lineno}: race-ok waiver with an "
                        "empty reason")
                continue
            held = set(w.held)
            if fi.requires:
                held.add(fi.requires)
            if gname not in held:
                where = ("under " + "/".join(sorted(set(w.held)))
                         if w.held else "with no lock held")
                problems.append(
                    f"{rel}:{w.lineno}: write to {key} (guarded by "
                    f"{gname!r}) {where} — wrap it in `with` of its "
                    f"guard, annotate the function `# concurrency: "
                    f"requires({gname})`, or waive with "
                    "# lint: race-ok(reason)")

    # requires() call-site discipline (Clang REQUIRES at the caller)
    for (rel, cls, fname), fi in sorted(
            an.funcs.items(), key=lambda kv: (kv[0][0], kv[0][1] or "",
                                              kv[0][2])):
        for cs in fi.calls:
            if cs.callee is None or cs.callee not in an.funcs:
                continue
            req = an.funcs[cs.callee].requires
            if not req:
                continue
            if cs.waived_race_ok is not None:
                if not cs.waived_race_ok:
                    problems.append(
                        f"{rel}:{cs.lineno}: race-ok waiver with an "
                        "empty reason")
                continue
            if req in cs.held or fi.requires == req \
                    or fname == "__init__":
                continue
            problems.append(
                f"{rel}:{cs.lineno}: calls {cs.callee[2]!r} (declared "
                f"`requires({req})`) without holding {req!r}")

    # DESIGN.md ownership map mirrors FIELDS, both directions
    rows = parse_design_ownership_table(design_path)
    if not rows:
        problems.append(
            "DESIGN.md has no 'Shared-state ownership map' table — the "
            "declared field ownership must be documented")
        return problems
    doc: Dict[str, tuple] = {}
    for f, g, wtext in rows:
        if f in doc:
            problems.append(
                f"field {f!r}: duplicate DESIGN.md ownership row")
        doc[f] = (g, wtext)
    for key, spec in sorted(fields.items()):
        want = "atomic" if spec.startswith("atomic:") else spec
        d = doc.get(key)
        if d is None:
            problems.append(
                f"field {key}: in locksan.FIELDS but missing from the "
                "DESIGN.md ownership map")
        elif d[0] != want:
            problems.append(
                f"field {key}: DESIGN.md guard column {d[0]!r} "
                f"disagrees with locksan.FIELDS ({want!r})")
        elif not d[1]:
            problems.append(
                f"field {key}: DESIGN.md ownership row has an empty "
                "writer-threads column")
    for f in sorted(set(doc) - set(fields)):
        problems.append(
            f"field {f!r}: documented in DESIGN.md but absent from "
            "locksan.FIELDS — stale doc row")
    return problems


def _thread_roots(an: _Analyzer) -> Dict[tuple, str]:
    """Thread entry points: rule (d)'s reader roots + every function
    handed to ``threading.Thread(target=...)``."""
    roots: Dict[tuple, str] = {}
    for rel, cls, name in _READER_ROOTS:
        key = (rel.replace("/", os.sep), cls, name)
        if key in an.funcs:
            roots[key] = f"reader:{cls}.{name}"
    for (rel, cls, _fname), fi in an.funcs.items():
        for recv, _lineno in fi.thread_targets:
            tkey = None
            if len(recv) == 2 and recv[0] in ("self", "cls"):
                tkey = (rel, cls, recv[1])
            elif len(recv) == 1:
                tkey = (rel, None, recv[0])
                if tkey not in an.funcs:
                    tkey = (rel, cls, recv[0])
            if tkey is not None and tkey in an.funcs:
                roots.setdefault(
                    tkey, f"thread:{(tkey[1] + '.') if tkey[1] else ''}"
                          f"{tkey[2]}")
    return roots


def _reachability(an: _Analyzer,
                  roots: Dict[tuple, str]) -> Dict[tuple, Set[str]]:
    reach: Dict[tuple, Set[str]] = {}
    for rkey, label in roots.items():
        seen = {rkey}
        frontier = [rkey]
        while frontier:
            k = frontier.pop()
            reach.setdefault(k, set()).add(label)
            fi = an.funcs.get(k)
            if fi is None:
                continue
            for cs in fi.calls:
                callee = cs.callee
                if callee is None or callee in seen:
                    continue
                cfi = an.funcs.get(callee)
                if cfi is None or cfi.is_async:
                    continue
                seen.add(callee)
                frontier.append(callee)
    return reach


def _infer_undeclared(an: _Analyzer) -> List[str]:
    """Inference pass: attributes assigned in ``__init__`` and written
    outside it from functions that two different thread entry points
    can reach must be DECLARED (guard / thread-confined / atomic) —
    the registry can't silently rot as code grows."""
    problems: List[str] = []
    fields = an.fields
    stem_rel = _stem_rels(an)
    target_rels = {stem_rel[s]: s for s in _FIELD_MODULES
                   if s in stem_rel}
    reach = _reachability(an, _thread_roots(an))

    init_attrs: Dict[tuple, Set[str]] = {}
    for (rel, cls, fname), fi in an.funcs.items():
        if cls is None or fname != "__init__" or rel not in target_rels:
            continue
        s = init_attrs.setdefault((rel, cls), set())
        for w in fi.writes:
            if w.scope == "self":
                s.add(w.name)

    # attr -> {labels of thread roots reaching a writer}
    writer_labels: Dict[tuple, Set[str]] = {}
    writer_sites: Dict[tuple, List[tuple]] = {}
    for (rel, cls, fname), fi in an.funcs.items():
        if cls is None or fname == "__init__" or rel not in target_rels:
            continue
        for w in fi.writes:
            if w.scope != "self":
                continue
            if (rel, cls) not in init_attrs \
                    or w.name not in init_attrs[(rel, cls)]:
                continue
            labels = reach.get((rel, cls, fname)) or {"driver"}
            k = (rel, cls, w.name)
            writer_labels.setdefault(k, set()).update(labels)
            writer_sites.setdefault(k, []).append((fname, w.lineno))

    for (rel, cls, attr), labels in sorted(writer_labels.items()):
        if len(labels) < 2:
            continue
        stem = target_rels[rel]
        key = f"{stem}.{cls}.{attr}"
        if key in fields:
            continue
        sites = ", ".join(f"{fn}:{ln}"
                          for fn, ln in sorted(writer_sites[
                              (rel, cls, attr)])[:4])
        problems.append(
            f"undeclared shared-field candidate {key}: mutated at "
            f"{sites} in functions reachable from "
            f"{'/'.join(sorted(labels))} — declare its guard in "
            "locksan.FIELDS (lock, thread:<owner>, or "
            "atomic:<reason>)")
    return problems


# ================================================================== driver

def analyze(repo_root: Optional[str] = None) -> _Analyzer:
    root = repo_root or _repo_root()
    an = _Analyzer(root)
    an.resolve_all()
    return an


def check(repo_root: Optional[str] = None,
          an: Optional[_Analyzer] = None) -> List[str]:
    root = repo_root or _repo_root()
    if an is None:
        an = analyze(root)
    problems: List[str] = []
    problems += _check_registry(an, os.path.join(root, "DESIGN.md"))
    problems += _check_order(an)
    problems += _check_blocking_under_lock(an)
    problems += _check_reader_discipline(an)
    problems += check_protocol_ops(an.files, an.funcs)
    problems += check_config_registry(an.files,
                                      os.path.join(root, "README.md"))
    problems += check_failpoint_registry(an.files)
    problems += _check_fields(an, os.path.join(root, "DESIGN.md"))
    problems += _infer_undeclared(an)
    return problems


def waiver_report(repo_root: Optional[str] = None,
                  an: Optional[_Analyzer] = None) -> List[tuple]:
    root = repo_root or _repo_root()
    if an is None:
        an = analyze(root)
    ops = _collect_protocol_ops(an.files)
    out: List[tuple] = []
    seen: Set[tuple] = set()
    for w in an.waivers:        # one waiver per line, not per call node
        key = w[:3]
        if key not in seen:
            seen.add(key)
            out.append(w)
    for name, (_v, lineno, reason) in sorted(ops.items()):
        if reason is not None:
            out.append(("allow-op", "_private/protocol.py", lineno,
                        reason))
    return out


def main() -> int:
    an = analyze()
    problems = check(an=an)
    for p in problems:
        print(f"concurrency-lint: {p}", file=sys.stderr)
    waivers = waiver_report(an=an)
    for kind, rel, lineno, reason in waivers:
        print(f"concurrency-lint: waiver {kind} at {rel}:{lineno}: "
              f"{reason}")
    if problems:
        print(f"concurrency-lint: {len(problems)} problem(s)",
              file=sys.stderr)
        return 1
    print(f"concurrency-lint: ok ({len(waivers)} waiver(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
