"""rtpu CLI — cluster inspection & ops.

Reference: ``python/ray/scripts/scripts.py`` (``ray status`` :1963,
``ray memory``, ``ray timeline``, ``ray list ...`` via the state CLI,
``experimental/state/state_cli.py``). argparse instead of click (no
extra deps); attaches to a live session by connecting a driver client
to its node unix socket (default: the most recent ``rtpu_session_*``).

Usage:
    python -m ray_tpu.scripts.cli status
    python -m ray_tpu.scripts.cli list tasks|actors|objects|pgs|nodes|workers
    python -m ray_tpu.scripts.cli summary tasks|actors
    python -m ray_tpu.scripts.cli memory
    python -m ray_tpu.scripts.cli timeline -o /tmp/trace.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Optional


def _find_session(session: Optional[str]) -> str:
    if session:
        return session
    candidates = sorted(glob.glob("/tmp/rtpu_session_*"),
                        key=os.path.getmtime, reverse=True)
    for c in candidates:
        if glob.glob(os.path.join(c, "node_*.sock")):
            return c
    raise SystemExit("no live rtpu session found (pass --session)")


def _connect(session_dir: str):
    from .._private import context as ctx
    from .._private import protocol as P
    from .._private.client import CoreClient
    from .._private.ids import JobID, WorkerID

    socks = sorted(glob.glob(os.path.join(session_dir, "node_*.sock")))
    if not socks:
        raise SystemExit(f"no node socket in {session_dir}")
    conn = P.connect_unix(socks[0])
    client = CoreClient(conn, JobID.from_random(), WorkerID.from_random(),
                        P.KIND_DRIVER)
    conn.send((P.REGISTER, (P.KIND_DRIVER, client.worker_id.binary(),
                            os.getpid())))
    client.start_reader()
    ctx.current_client = client
    return client


def _print_table(rows, columns) -> None:
    if not rows:
        print("(empty)")
        return
    widths = [max(len(str(r.get(c, ""))) for r in rows + [{c: c}])
              for c in columns]
    header = "  ".join(c.ljust(w) for c, w in zip(columns, widths))
    print(header)
    print("-" * len(header))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(w)
                        for c, w in zip(columns, widths)))


def cmd_status(client, args) -> None:
    total = client.cluster_info("resources_total") or {}
    avail = client.cluster_info("resources_available") or {}
    nodes = client.cluster_info("nodes") or []
    alive = sum(1 for n in nodes if n.get("alive"))
    print(f"Nodes: {alive} alive / {len(nodes)} total")
    print("Resources:")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0.0):g} / {total[k]:g} available")


def cmd_list(client, args) -> None:
    from ..state import (list_actors, list_jobs, list_nodes, list_objects,
                         list_placement_groups, list_tasks, list_workers)
    what = args.what
    if what == "tasks":
        rows = list_tasks(limit=args.limit)
        cols = ["task_id", "name", "state", "is_actor_task"]
    elif what == "actors":
        rows = list_actors(limit=args.limit)
        cols = ["actor_id", "class_name", "name", "state", "num_restarts"]
    elif what == "objects":
        rows = list_objects(limit=args.limit)
        cols = ["object_id", "node_id", "size", "callsite", "creator"]
    elif what in ("pgs", "placement_groups"):
        rows = list_placement_groups(limit=args.limit)
        cols = ["pg_id", "strategy", "bundles"]
    elif what == "nodes":
        rows = [{**n, "node_id": n["node_id"].hex()
                 if hasattr(n["node_id"], "hex") else n["node_id"]}
                for n in list_nodes()]
        cols = ["node_id", "alive", "resources"]
    elif what == "workers":
        rows = list_workers()
        cols = ["worker_id", "pid", "state", "actor_id"]
    elif what == "jobs":
        rows = list_jobs()
        cols = ["job_id", "driver_pid", "start_time", "end_time"]
    else:
        raise SystemExit(f"unknown list target {what!r}")
    if args.format == "json":
        print(json.dumps(rows, default=str, indent=2))
    else:
        _print_table(rows, cols)


def cmd_summary(client, args) -> None:
    from ..state import summarize_actors, summarize_tasks
    summary = (summarize_tasks() if args.what == "tasks"
               else summarize_actors())
    print(json.dumps(summary, indent=2, default=str))


def cmd_metrics(client, args) -> None:
    """Cluster-wide runtime metrics: Prometheus text (default) or the
    per-metric summary rollup."""
    if args.format == "summary":
        from ..state import summarize_metrics
        print(json.dumps(summarize_metrics(), indent=2, default=str))
    else:
        from ..util.metrics import export_prometheus
        print(export_prometheus(), end="")


def _fmt_bytes(n) -> str:
    if n is None:
        return "?"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} GiB"


def _fmt_ref_types(rt: dict) -> str:
    return ",".join(f"{k}:{v}" for k, v in sorted((rt or {}).items())) \
        or "-"


def cmd_memory(client, args) -> None:
    """Object ownership & memory introspection (reference: ``ray
    memory``): grouped byte totals by creation callsite / creator /
    node, per-object rows with ref types, leak findings, and per-node
    store stats."""
    from ..state import list_objects, memory_summary
    summary = memory_summary(group_by=args.group_by, top_k=args.limit,
                             sort_by=args.sort_by)
    objects = None
    if args.objects:
        objects = list_objects(limit=10**9)
        objects.sort(key=lambda r: -(r.get("size") or 0))
        objects = objects[:args.limit]
    if args.format == "json":
        if objects is not None:
            summary = {**summary, "objects": objects}
        print(json.dumps(summary, default=str, indent=2))
        return
    _render_memory_summary(summary, args.group_by, args.limit,
                           args.sort_by)
    if objects is not None:
        print("\nObjects (largest first):")
        _print_table(
            [{**o, "size": _fmt_bytes(o.get("size")),
              "ref_types": _fmt_ref_types(o.get("ref_types"))}
             for o in objects],
            ["object_id", "size", "callsite", "creator", "ref_types",
             "pinned_in_store", "spilled"])
    _render_memory_leaks(summary)


def _render_memory_summary(summary, group_by, limit, sort_by) -> None:
    """Memory rollup renderer — live (`rtpu memory`) or from a bundle
    (`rtpu autopsy`)."""
    print(f"{summary['total_objects']} tracked object(s), "
          f"{_fmt_bytes(summary['total_bytes'])} cluster-wide")
    for node_hex, st in sorted((summary.get("stores") or {}).items()):
        print(f"  store {node_hex[:12]}: "
              f"{_fmt_bytes(st.get('used_bytes'))} / "
              f"{_fmt_bytes(st.get('capacity_bytes'))} used, "
              f"{st.get('num_objects', 0)} object(s), "
              f"{st.get('num_spilled', 0)} spilled")
    order = ("most objects" if sort_by == "count" else "most bytes")
    print(f"\nBy {group_by} (top {limit}, {order} first):")
    _print_table(
        [{group_by: g["key"], "objects": g["objects"],
          "bytes": _fmt_bytes(g["bytes"]),
          "ref_types": _fmt_ref_types(g["ref_types"])}
         for g in summary["groups"]],
        [group_by, "objects", "bytes", "ref_types"])
    if summary.get("dropped_groups"):
        print(f"  (+{summary['dropped_groups']} more group(s); raise "
              "--limit)")


def _render_memory_leaks(summary) -> None:
    for leak in summary.get("leaks") or []:
        print(f"  ! LEAK [{leak.get('cause')}] object "
              f"{str(leak.get('object_id'))[:12]} "
              f"size={_fmt_bytes(leak.get('size'))} "
              f"callsite={leak.get('callsite')}")


def cmd_timeline(client, args) -> None:
    from ..state import timeline
    out = args.output or "/tmp/rtpu_timeline.json"
    timeline(out)
    print(f"wrote {out} (open in chrome://tracing or ui.perfetto.dev)")


def cmd_stack(client, args) -> None:
    """Cluster-wide thread dump (reference: ``ray stack``): every
    node/worker/driver process, deduplicated by identical stacks."""
    from ..state import cluster_stacks
    result = cluster_stacks(timeout_s=args.timeout)
    if args.format == "json":
        print(json.dumps(result, default=str, indent=2))
        return
    groups = result.get("groups") or []
    n_procs = sum(len(d) for d in (result.get("nodes") or {}).values())
    print(f"{n_procs} process(es) on {len(result.get('nodes') or {})} "
          f"node(s), {len(groups)} distinct stack(s)\n")
    for g in groups:
        where = ", ".join(
            f"{t.get('kind')}:{str(t.get('worker_id') or t.get('node'))[:8]}"
            f"/{t.get('thread')}" for t in g["threads"][:6])
        more = len(g["threads"]) - 6
        if more > 0:
            where += f", +{more} more"
        print(f"=== {g['count']} thread(s): {where}")
        for fr in g["frames"]:
            print(f"    {fr}")
        print()


def cmd_profile(client, args) -> None:
    """Cluster-wide sampling wall-clock profiler; prints the hottest
    collapsed stacks and optionally writes flamegraph / Chrome files."""
    from .._private import debugging
    from ..state import profile
    report = profile(duration_s=args.duration,
                     interval_ms=args.interval_ms,
                     task_filter=args.task_filter,
                     collapsed_file=args.output,
                     chrome_trace_file=args.chrome)
    collapsed = report.get("collapsed") or {}
    if args.format == "json":
        print(json.dumps(report, default=str, indent=2))
        return
    print(f"sampled {report.get('num_samples', 0)} ticks over "
          f"{report.get('duration_s')}s; {len(collapsed)} distinct "
          "stack(s)\n")
    for count, frames in debugging.top_stacks(collapsed, n=args.top):
        print(f"--- {count} sample(s):")
        for fr in frames:
            print(f"    {fr}")
        print()
    if args.output:
        print(f"wrote collapsed stacks to {args.output} "
              "(feed to flamegraph.pl / speedscope)")
    if args.chrome:
        print(f"wrote Chrome trace to {args.chrome}")


def cmd_coll_debug(client, args) -> None:
    """Collective flight-recorder surface: in-flight op watermarks
    across every rank, hang verdicts (dead rank / lost chunk / lagging
    rank), and optionally the raw recent event ring per process."""
    from ..state import collective_health, flight_records
    report = collective_health(timeout_s=args.timeout)
    records = flight_records(args.timeout) if args.records else None
    if args.format == "json":
        if records is not None:
            report = {**report, "records": records}
        print(json.dumps(report, default=str, indent=2))
        return
    _render_coll(report)
    if records is not None:
        _render_coll_records(records, args.limit)


def _render_coll(report) -> None:
    """Collective-health renderer — live or from a bundle."""
    ops = report.get("ops") or []
    verdicts = report.get("verdicts") or []
    print(f"{report.get('processes', 0)} process(es) replied, "
          f"{len(ops)} collective op(s) observed, "
          f"{len(verdicts)} stuck")
    for op in ops:
        state = "STUCK" if op.get("stuck_ranks") else "done"
        print(f"\n=== {op.get('op')} group={op.get('group')} "
              f"seq={op.get('seq')} algo={op.get('algo')} "
              f"nbytes={op.get('nbytes')} [{state}] "
              f"({len(op.get('done_ranks') or [])}/{op.get('world')} "
              "ranks finished)")
        for rank, mark in sorted((op.get("stuck_ranks") or {}).items()):
            print(f"    rank {rank}: {mark}")
    for v in verdicts:
        print(f"\n!!! [{v.get('verdict')}] {v.get('message')}")
        for fr in v.get("stack") or []:
            print(f"        {fr}")


def _render_coll_records(recs, limit: int) -> None:
    for node_hex, snaps in sorted((recs.get("nodes") or {}).items()):
        for snap in snaps or []:
            recent = snap.get("recent") or []
            if not recent:
                continue
            print(f"\n--- {snap.get('kind')} "
                  f"{str(snap.get('worker_id'))[:12]} on "
                  f"{node_hex}: last {len(recent)} event(s)")
            for ev in recent[-limit:]:
                print(f"    {ev.get('ts'):.6f} {str(ev.get('kind')):8s} "
                      f"{ev.get('key')} ({ev.get('info')})")


def cmd_serve_status(client, args) -> None:
    """Serving health plane: per-deployment latency/queue-wait
    percentiles (streaming digests), queue depth, error rate, replica
    table — the autoscaling signal tuple. ``--trend N`` adds head/tail
    movement over the trailing N seconds of retained history."""
    from ..state import serve_health
    health = serve_health(trend=args.trend)
    if args.format == "json":
        print(json.dumps(health, default=str, indent=2))
        return
    _render_serve(health)


def _render_serve(health) -> None:
    """Serve table renderer — live or from a bundle (`rtpu autopsy`)."""
    deps = health.get("deployments") or {}
    if not deps:
        print("no serve deployments observed")
        return

    def ms(d, q):
        v = (d or {}).get(q)
        return f"{v * 1000:.1f}ms" if v is not None else "-"

    rows = []
    for name in sorted(deps):
        d = deps[name]
        rows.append({
            "deployment": name,
            "replicas": len(d.get("replicas") or []),
            "queue": f"{d.get('queue_depth', 0):g}",
            "reqs": f"{d.get('requests_total', 0):g}",
            "err_rate": f"{d.get('error_rate', 0.0):.1%}",
            "p50": ms(d.get("latency"), "p50"),
            "p95": ms(d.get("latency"), "p95"),
            "p99": ms(d.get("latency"), "p99"),
            "qwait_p99": ms(d.get("queue_wait"), "p99"),
            "batch_p50": (f"{(d.get('batch_size') or {}).get('p50', 0):.1f}"
                          if d.get("batch_size") else "-"),
        })
    _print_table(rows, ["deployment", "replicas", "queue", "reqs",
                        "err_rate", "p50", "p95", "p99", "qwait_p99",
                        "batch_p50"])
    for name, tr in sorted((health.get("trend") or {}).items()):
        parts = []
        for field in ("queue_depth", "latency_p95", "queue_wait_p95",
                      "request_rate"):
            p = tr.get(field)
            if p:
                ratio = (f" ({p['ratio']}x)"
                         if p.get("ratio") is not None else "")
                parts.append(f"{field} {p['head']:g}->{p['tail']:g}"
                             f"{ratio}")
        if parts:
            print(f"  trend[{tr.get('window_s')}s] {name}: "
                  + ", ".join(parts))
    if health.get("worst"):
        print(f"\nworst deployment: {health['worst']}")


def cmd_requests(client, args) -> None:
    """Recent serve access-log rows gathered from every replica's ring
    (request_id, deployment, route, status, latency, queue wait)."""
    from ..state import serve_requests
    rows = serve_requests(limit=args.limit, slow=args.slow,
                          errors=args.errors)
    if args.format == "json":
        print(json.dumps(rows, default=str, indent=2))
        return
    if not rows:
        print("no request rows (serve idle, or request_log_capacity=0)")
        return
    _print_table(
        [{**r,
          "latency": f"{r.get('latency_s', 0) * 1000:.1f}ms",
          "queue_wait": f"{r.get('queue_wait_s', 0) * 1000:.1f}ms",
          "batch": r.get("batch_size") or "-",
          "error": (str(r.get("error"))[:40] if r.get("error") else "")}
         for r in rows],
        ["request_id", "deployment", "replica", "route", "proto",
         "status", "latency", "queue_wait", "batch", "error"])


def cmd_doctor(client, args) -> None:
    """Correlated cluster health report: nodes, resources, task/actor
    rollups, stall diagnoses, trend movements, recent alerts,
    telemetry highlights."""
    from ..state import health_report
    rep = health_report()
    if args.format == "json":
        print(json.dumps(rep, default=str, indent=2))
        return
    _render_doctor(rep)


def _render_doctor(rep) -> None:
    """Text renderer of one doctor report — live (`rtpu doctor`) or
    replayed from a bundle (`rtpu autopsy`)."""
    verdict = "HEALTHY" if rep["healthy"] else "UNHEALTHY"
    print(f"cluster: {verdict}")
    for p in rep["problems"]:
        print(f"  ! {p}")
    nodes = rep["nodes"]
    print(f"nodes: {nodes['alive']} alive, {nodes['dead']} dead")
    res = rep["resources"]
    for k in sorted(res["total"]):
        print(f"  {k}: {res['available'].get(k, 0.0):g} / "
              f"{res['total'][k]:g} available")
    print(f"tasks: {json.dumps(rep['tasks'].get('by_state', {}))}")
    print(f"actors: {json.dumps(rep['actors'].get('by_state', {}))}")
    if rep["metrics"]:
        print(f"telemetry: {json.dumps(rep['metrics'])}")
    for t in rep.get("trends") or []:
        ratio = (f"{t['ratio']}x " if t.get("ratio") else "")
        print(f"  TREND [{t.get('kind')}] {ratio}{t.get('message')}")
    for ev in rep["stalls"]:
        print(f"  STALL [{ev.get('cause')}] {ev.get('message')}")
    for v in (rep.get("collectives") or {}).get("verdicts", []):
        print(f"  COLLECTIVE [{v.get('verdict')}] {v.get('message')}")
    srv = rep.get("serve") or {}
    if srv.get("deployments"):
        worst = srv.get("worst")
        wd = (srv["deployments"].get(worst) or {}) if worst else {}
        lat = wd.get("latency") or {}
        print(f"serve: {len(srv['deployments'])} deployment(s); "
              f"worst: {worst} "
              f"(err_rate={wd.get('error_rate', 0.0):.1%}, "
              f"p99={(lat.get('p99') or 0.0) * 1000:.1f}ms)")
    rec = rep.get("recovery") or {}
    if any((rec.get("collective_reforms"), rec.get("actor_restores"),
            rec.get("actor_checkpoints"),
            rec.get("exhausted_restart_budgets"))):
        print("recovery: "
              f"{rec.get('collective_reforms', 0):g} group reform(s), "
              f"{rec.get('actor_checkpoints', 0):g} checkpoint(s), "
              f"{rec.get('actor_restores', 0):g} actor restore(s)")
        for ev in rec.get("recent_reforms", []):
            print(f"  REFORM {ev.get('message')}")
        for a in rec.get("exhausted_restart_budgets", []):
            print(f"  ! actor {str(a.get('actor_id'))[:12]} "
                  f"({a.get('class_name')}) dead after "
                  f"{a.get('num_restarts')} restart(s) — budget "
                  "exhausted")
    for ev in rep["alerts"]:
        print(f"  {ev.get('severity')} [{ev.get('label')}] "
              f"{ev.get('message')}")


def _parse_when(spec, now: float):
    """``--since/--until`` forms: epoch seconds (float), or relative
    ``30s``/``5m``/``2h`` meaning that long before now."""
    if spec is None:
        return None
    s = str(spec).strip()
    try:
        mult = {"s": 1.0, "m": 60.0, "h": 3600.0}.get(s[-1:])
        if mult is not None:
            return now - float(s[:-1]) * mult
        return float(s)
    except ValueError:
        raise SystemExit(f"bad time spec {spec!r} (epoch seconds, or "
                         "relative like 120s / 5m / 1h)")


def cmd_events(client, args) -> None:
    """Structured cluster events with time-window filtering
    (``--since/--until``); the ring's eviction counter says whether
    older rows were lost to retention."""
    import time as _time

    from ..state import events_stats, list_events
    now = _time.time()
    filters = {}
    if args.label:
        filters["label"] = args.label
    if args.severity:
        filters["severity"] = args.severity
    rows = list_events(filters or None, limit=args.limit,
                       since=_parse_when(args.since, now),
                       until=_parse_when(args.until, now))
    if args.format == "json":
        print(json.dumps(rows, default=str, indent=2))
        return
    for r in rows:
        ts = _time.strftime("%H:%M:%S",
                            _time.localtime(r.get("timestamp") or 0))
        print(f"{ts} {r.get('severity', '?'):7s} "
              f"[{r.get('label')}] {r.get('message')}")
    stats = events_stats()
    if stats.get("evicted"):
        print(f"({stats['evicted']} older event(s) evicted from the "
              f"{stats.get('capacity')}-slot ring — see "
              "rtpu_events_evicted_total)")


def cmd_history(client, args) -> None:
    """Windowed metric time series from the retention ring
    (``state.metrics_history``): aligned points per series, with
    rate/delta shaping for counters."""
    from ..state import metrics_history
    res = metrics_history(name=args.metric, window=args.window,
                          step=args.step, shape=args.shape)
    if args.format == "json":
        print(json.dumps(res, default=str, indent=2))
        return
    series = res.get("series") or []
    print(f"{len(series)} series, step {res.get('step_s')}s, "
          f"window {res.get('window_s')}s")
    for s in series[:args.limit]:
        tags = ",".join(f"{k}={v}" for k, v in sorted(s["tags"].items()))
        pts = s["points"]
        shown = pts[-8:]

        def fmt(v):
            if isinstance(v, dict):
                return (f"p95={v.get('p95'):.4g}" if "p95" in v
                        else str(v))
            return f"{v:.6g}"

        print(f"  {s['name']}{{{tags}}} [{s['kind']}"
              + (f", {s.get('shape')}" if s.get("shape") else "")
              + f"] {len(pts)} pt(s): "
              + " ".join(fmt(v) for _ts, v in shown))


def cmd_debug_bundle(client, args) -> None:
    """Capture a black-box post-mortem bundle of everything the session
    knows (metrics history, events, stacks, flight recorder, access
    logs, spans, memory ledger, config) into one portable tar."""
    import time as _time

    from .._private import debug_bundle
    out = args.output or os.path.abspath(
        f"rtpu_bundle_manual_{int(_time.time())}.tar.gz")
    path = debug_bundle.capture(out, debug_bundle.ClientSource(client),
                                reason="manual",
                                timeout_s=args.timeout)
    print(f"wrote {path} (inspect with `rtpu autopsy {path}`)")


def cmd_autopsy(args) -> None:
    """Offline post-mortem: replay a captured bundle through the
    doctor/serve/coll-debug/memory surfaces with NO live cluster."""
    from .._private import debug_bundle
    bundle = debug_bundle.load(args.bundle)
    rep = debug_bundle.build_autopsy(bundle,
                                     trend_window=args.trend)
    if args.format == "json":
        print(json.dumps(rep, default=str, indent=2))
        return
    man = rep["manifest"]
    import time as _time
    created = _time.strftime("%Y-%m-%d %H:%M:%S", _time.localtime(
        man.get("created_ts") or 0))
    print(f"bundle: {args.bundle}")
    print(f"  captured {created} (reason: {man.get('reason')}, "
          f"format v{man.get('format_version')}, "
          f"{len(man.get('sections') or [])} section(s))")
    bad = [s["name"] for s in man.get("sections") or []
           if not s.get("ok")]
    if bad:
        print(f"  ! sections that failed capture: {', '.join(bad)}")
    trigger = rep.get("trigger") or {}
    extra = {k: v for k, v in trigger.items() if k != "reason"}
    if extra:
        print("  trigger: " + ", ".join(f"{k}={v}"
                                        for k, v in sorted(extra.items())))
    print("\n== doctor (replayed offline) ==")
    _render_doctor(rep["doctor"])
    coll = rep.get("collectives") or {}
    if coll.get("ops") or coll.get("verdicts"):
        print("\n== collectives ==")
        _render_coll(coll)
    serve = rep.get("serve") or {}
    if serve.get("deployments"):
        print("\n== serve ==")
        _render_serve(serve)
    mem = rep.get("memory") or {}
    if mem.get("total_objects"):
        print("\n== memory ==")
        _render_memory_summary(mem, mem.get("group_by", "callsite"),
                               20, mem.get("sort_by", "bytes"))
        _render_memory_leaks(mem)
    stats = rep.get("events_stats") or {}
    if stats.get("evicted"):
        print(f"\n({stats['evicted']} event(s) had already been evicted "
              "from the ring before capture)")


def cmd_start(args) -> None:
    """Start a node process: ``rtpu start --head [--gcs-port N]`` or
    ``rtpu start --address HOST:PORT`` (reference: ``ray start``,
    ``python/ray/scripts/scripts.py``). Runs in the foreground unless
    --daemon; kill with SIGTERM / ``rtpu stop``."""
    import subprocess

    from .._private import main as node_main

    fwd = []
    if args.head:
        fwd += ["--head", "--gcs-port", str(args.gcs_port)]
    else:
        fwd += ["--address", args.address]
    fwd += ["--node-port", str(args.node_port),
            "--advertise-host", args.advertise_host]
    if args.num_cpus is not None:
        fwd += ["--num-cpus", str(args.num_cpus)]
    if args.num_tpus is not None:
        fwd += ["--num-tpus", str(args.num_tpus)]
    if args.resources:
        fwd += ["--resources", args.resources]
    if args.daemon:
        pid_file = args.pid_file or "/tmp/rtpu_node.pid"
        proc = subprocess.Popen([sys.executable, "-m",
                                 "ray_tpu._private.main"] + fwd,
                                start_new_session=True)
        with open(pid_file, "w") as f:
            f.write(str(proc.pid))
        print(f"node started pid={proc.pid} (pid file {pid_file})")
        return
    raise SystemExit(node_main.main(fwd))


def cmd_stop(args) -> None:
    import signal

    pid_file = args.pid_file or "/tmp/rtpu_node.pid"
    try:
        with open(pid_file) as f:
            pid = int(f.read().strip())
    except OSError:
        raise SystemExit(f"no pid file at {pid_file}")
    try:
        os.kill(pid, signal.SIGTERM)
        print(f"sent SIGTERM to {pid}")
    except ProcessLookupError:
        print(f"process {pid} already gone")
    os.unlink(pid_file)


def _job_client(args):
    from ..job.client import JobSubmissionClient
    address = getattr(args, "job_address", None)
    if not address and getattr(args, "address", None):
        # resolve the REST endpoint through the cluster's GCS
        from .._private.gcs_service import RemoteControlPlane
        gcs = RemoteControlPlane(args.address)
        try:
            raw = gcs.kv_get(b"__rtpu_job_api")
        finally:
            gcs.close()
        if raw is None:
            raise SystemExit("cluster has no job API (head not started "
                             "with a job server?)")
        address = raw.decode()
    if not address:
        raise SystemExit("pass --address (cluster GCS) or --job-address")
    return JobSubmissionClient(address)


def cmd_submit(args) -> None:
    client = _job_client(args)
    runtime_env = {}
    if args.working_dir:
        runtime_env["working_dir"] = args.working_dir
    if args.env:
        runtime_env["env_vars"] = dict(kv.split("=", 1) for kv in args.env)
    job_id = client.submit_job(
        entrypoint=" ".join(args.entrypoint),
        runtime_env=runtime_env or None,
        submission_id=args.submission_id)
    print(f"submitted {job_id}")
    if args.no_wait:
        return
    rec = client.wait_until_finished(job_id, timeout=args.timeout)
    sys.stdout.write(client.get_job_logs(job_id))
    print(f"job {job_id} {rec['status']} (rc={rec.get('return_code')})")
    if rec["status"] != "SUCCEEDED":
        raise SystemExit(1)


def cmd_job(args) -> None:
    client = _job_client(args)
    if args.job_command == "status":
        print(json.dumps(client.get_job_status(args.job_id), indent=2))
    elif args.job_command == "logs":
        sys.stdout.write(client.get_job_logs(args.job_id))
    elif args.job_command == "stop":
        print(json.dumps({"stopped": client.stop_job(args.job_id)}))
    elif args.job_command == "list":
        _print_table(client.list_jobs(),
                     ["job_id", "status", "entrypoint", "return_code"])


def cmd_lint(args) -> None:
    """Run the repo's static lints: the observability-registry lint
    (check_metrics) and the concurrency lint (check_concurrency) —
    the same pair tier-1 gates on."""
    from . import check_concurrency, check_metrics
    an = check_concurrency.analyze()   # one package analysis, reused
    rc = 0
    for name, problems in (
            ("metric-lint", check_metrics.check()),
            ("concurrency-lint", check_concurrency.check(an=an))):
        for p in problems:
            print(f"{name}: {p}", file=sys.stderr)
        if problems:
            print(f"{name}: {len(problems)} problem(s)", file=sys.stderr)
            rc = 1
        else:
            print(f"{name}: ok")
    for kind, rel, lineno, reason in check_concurrency.waiver_report(
            an=an):
        print(f"concurrency-lint: waiver {kind} at {rel}:{lineno}: "
              f"{reason}")
    raise SystemExit(rc)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="rtpu",
                                     description="ray_tpu cluster CLI")
    parser.add_argument("--session", help="session dir (default: latest)")
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("status")
    sub.add_parser(
        "lint", help="static lints: metric registry + concurrency/lock-order")
    p_list = sub.add_parser("list")
    p_list.add_argument("what")
    p_list.add_argument("--limit", type=int, default=100)
    p_list.add_argument("--format", choices=("table", "json"),
                        default="table")
    p_sum = sub.add_parser("summary")
    p_sum.add_argument("what", choices=("tasks", "actors"))
    p_met = sub.add_parser("metrics",
                           help="runtime metrics (Prometheus or summary)")
    p_met.add_argument("--format", choices=("prom", "summary"),
                       default="prom")
    p_mem = sub.add_parser("memory",
                           help="object ownership & memory "
                           "introspection (ray memory)")
    p_mem.add_argument("--group-by",
                       choices=("callsite", "creator", "node"),
                       default="callsite")
    p_mem.add_argument("--sort-by", choices=("bytes", "count"),
                       default="bytes",
                       help="group ordering: byte total or object count")
    p_mem.add_argument("--objects", action="store_true",
                       help="also print per-object rows")
    p_mem.add_argument("--limit", type=int, default=20)
    p_mem.add_argument("--format", choices=("table", "json"),
                       default="table")
    p_tl = sub.add_parser("timeline")
    p_tl.add_argument("-o", "--output")
    p_stack = sub.add_parser("stack",
                             help="cluster-wide thread dump (ray stack)")
    p_stack.add_argument("--timeout", type=float, default=5.0)
    p_stack.add_argument("--format", choices=("text", "json"),
                         default="text")
    p_prof = sub.add_parser("profile",
                            help="sampling wall-clock profiler across "
                            "all workers")
    p_prof.add_argument("--duration", type=float, default=5.0)
    p_prof.add_argument("--interval-ms", type=float, default=None)
    p_prof.add_argument("--task-filter", default=None,
                        help="only sample while a task whose name "
                        "contains this substring is running")
    p_prof.add_argument("--top", type=int, default=10)
    p_prof.add_argument("-o", "--output", default=None,
                        help="write flamegraph collapsed stacks here")
    p_prof.add_argument("--chrome", default=None,
                        help="write a Chrome trace JSON here")
    p_prof.add_argument("--format", choices=("text", "json"),
                        default="text")
    p_doc = sub.add_parser("doctor",
                           help="correlated cluster health report")
    p_doc.add_argument("--format", choices=("text", "json"),
                       default="text")
    p_coll = sub.add_parser("coll-debug",
                            help="collective flight recorder: watermark"
                            " diff + hang/straggler verdicts")
    p_coll.add_argument("--timeout", type=float, default=3.0)
    p_coll.add_argument("--records", action="store_true",
                        help="also dump each process's recent "
                        "flight-recorder event ring")
    p_coll.add_argument("--limit", type=int, default=40,
                        help="ring events shown per process with "
                        "--records")
    p_coll.add_argument("--format", choices=("text", "json"),
                        default="text")

    p_srv = sub.add_parser("serve-status",
                           help="per-deployment serving health: "
                           "latency/queue percentiles, error rate, "
                           "replica table")
    p_srv.add_argument("--format", choices=("table", "json"),
                       default="table")
    p_srv.add_argument("--trend", type=float, default=None,
                       metavar="SECONDS",
                       help="attach head/tail movement over this "
                       "trailing history window")
    p_ev = sub.add_parser("events",
                          help="structured cluster events with "
                          "--since/--until time windows")
    p_ev.add_argument("--since", default=None,
                      help="epoch seconds or relative (120s / 5m / 1h)")
    p_ev.add_argument("--until", default=None,
                      help="epoch seconds or relative (120s / 5m / 1h)")
    p_ev.add_argument("--label", default=None)
    p_ev.add_argument("--severity", default=None,
                      choices=("DEBUG", "INFO", "WARNING", "ERROR"))
    p_ev.add_argument("--limit", type=int, default=100)
    p_ev.add_argument("--format", choices=("text", "json"),
                      default="text")
    p_hist = sub.add_parser("history",
                            help="windowed metric time series from the "
                            "retention ring (rate/delta shaping)")
    p_hist.add_argument("metric", nargs="?", default=None,
                        help="metric name (default: all retained)")
    p_hist.add_argument("--window", type=float, default=None,
                        help="trailing seconds (default: finest ring)")
    p_hist.add_argument("--step", type=float, default=None,
                        help="minimum seconds per point")
    p_hist.add_argument("--shape", choices=("value", "rate", "delta"),
                        default="value")
    p_hist.add_argument("--limit", type=int, default=40,
                        help="series shown (text format)")
    p_hist.add_argument("--format", choices=("text", "json"),
                        default="text")
    p_bundle = sub.add_parser("debug-bundle",
                              help="capture a black-box post-mortem "
                              "bundle (one portable tar)")
    p_bundle.add_argument("-o", "--output", default=None)
    p_bundle.add_argument("--timeout", type=float, default=2.0,
                          help="per-fan-out budget (stacks, "
                          "flight records)")
    p_autopsy = sub.add_parser("autopsy",
                               help="replay a captured bundle offline: "
                               "doctor/serve/coll-debug/memory with no "
                               "live cluster")
    p_autopsy.add_argument("bundle", help="path to a debug-bundle tar")
    p_autopsy.add_argument("--trend", type=float, default=None,
                           metavar="SECONDS",
                           help="trend window for the replayed doctor")
    p_autopsy.add_argument("--format", choices=("text", "json"),
                           default="text")
    p_req = sub.add_parser("requests",
                           help="recent serve access-log rows "
                           "(request ids, latency, queue wait)")
    p_req.add_argument("--slow", action="store_true",
                       help="only rows at/over the slow-request "
                       "threshold")
    p_req.add_argument("--errors", action="store_true",
                       help="only failed requests")
    p_req.add_argument("--limit", type=int, default=50)
    p_req.add_argument("--format", choices=("table", "json"),
                       default="table")

    p_start = sub.add_parser("start", help="start a cluster node process")
    p_start.add_argument("--head", action="store_true")
    p_start.add_argument("--address", default=None)
    p_start.add_argument("--gcs-port", type=int, default=6379)
    p_start.add_argument("--node-port", type=int, default=0)
    p_start.add_argument("--advertise-host", default="127.0.0.1",
                         help="address other hosts reach this node at "
                         "(set to this machine's network IP for "
                         "multi-host clusters)")
    p_start.add_argument("--num-cpus", type=float, default=None)
    p_start.add_argument("--num-tpus", type=float, default=None)
    p_start.add_argument("--resources", default=None)
    p_start.add_argument("--daemon", action="store_true")
    p_start.add_argument("--pid-file", default=None)
    p_stop = sub.add_parser("stop", help="stop a daemonized node")
    p_stop.add_argument("--pid-file", default=None)

    p_sub = sub.add_parser("submit", help="submit a job to a cluster")
    p_sub.add_argument("--address", default=None,
                       help="cluster GCS host:port")
    p_sub.add_argument("--job-address", default=None,
                       help="job REST host:port (skips GCS lookup)")
    p_sub.add_argument("--working-dir", default=None)
    p_sub.add_argument("--env", action="append", default=[],
                       metavar="KEY=VALUE")
    p_sub.add_argument("--submission-id", default=None)
    p_sub.add_argument("--no-wait", action="store_true")
    p_sub.add_argument("--timeout", type=float, default=600.0)
    p_sub.add_argument("entrypoint", nargs=argparse.REMAINDER,
                       help="command to run (prefix with --)")

    p_job = sub.add_parser("job", help="job status/logs/stop/list")
    p_job.add_argument("job_command",
                       choices=("status", "logs", "stop", "list"))
    p_job.add_argument("job_id", nargs="?", default=None)
    p_job.add_argument("--address", default=None)
    p_job.add_argument("--job-address", default=None)
    p_job.set_defaults(needs_job_id=("status", "logs", "stop"))

    args = parser.parse_args(argv)
    if args.command == "lint":
        cmd_lint(args)
        return
    if args.command == "autopsy":
        # offline by design: reads only the bundle, never a session
        cmd_autopsy(args)
        return
    if args.command == "start":
        cmd_start(args)
        return
    if args.command == "stop":
        cmd_stop(args)
        return
    if args.command == "submit":
        if args.entrypoint and args.entrypoint[0] == "--":
            args.entrypoint = args.entrypoint[1:]
        if not args.entrypoint:
            raise SystemExit("no entrypoint given (rtpu submit ... -- cmd)")
        cmd_submit(args)
        return
    if args.command == "job":
        if args.job_command in args.needs_job_id and not args.job_id:
            raise SystemExit(f"rtpu job {args.job_command} needs a job id")
        cmd_job(args)
        return
    session = _find_session(args.session)
    client = _connect(session)
    try:
        {"status": cmd_status, "list": cmd_list, "summary": cmd_summary,
         "memory": cmd_memory, "timeline": cmd_timeline,
         "metrics": cmd_metrics, "stack": cmd_stack,
         "profile": cmd_profile, "doctor": cmd_doctor,
         "coll-debug": cmd_coll_debug,
         "serve-status": cmd_serve_status,
         "requests": cmd_requests,
         "events": cmd_events,
         "history": cmd_history,
         "debug-bundle": cmd_debug_bundle}[args.command](
             client, args)
    finally:
        try:
            client.close()
        except Exception:
            pass


if __name__ == "__main__":
    main()
