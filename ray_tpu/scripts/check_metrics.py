"""Metric-registry lint: every runtime metric the code defines must be
a valid Prometheus name AND documented in README.md's Observability
registry — new instrumentation can't ship undocumented.

Wired in as a tier-1 test (``tests/test_metric_lint.py``); also runnable
standalone: ``python -m ray_tpu.scripts.check_metrics``.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set

# Prometheus metric-name grammar (https://prometheus.io/docs/concepts/
# data_model/) narrowed to this repo's convention: rtpu_ prefix,
# lower-snake-case. `_bucket`/`_sum`/`_count`/`_total` suffixes are part
# of the name as defined.
_NAME_RE = re.compile(r"^rtpu_[a-z][a-z0-9_]*$")
_README_NAME_RE = re.compile(r"`(rtpu_[A-Za-z0-9_:]+)`")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def collect_defined_metrics(pkg_dir: str) -> Dict[str, str]:
    """All metric names registered via ``telemetry.define(kind, name,
    ...)`` anywhere under the package, mapped to the defining file."""
    out: Dict[str, str] = {}
    for dirpath, _dirs, files in os.walk(pkg_dir):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            try:
                with open(path) as f:
                    tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                name = (fn.attr if isinstance(fn, ast.Attribute)
                        else fn.id if isinstance(fn, ast.Name) else None)
                if name != "define" or len(node.args) < 2:
                    continue
                arg = node.args[1]
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and arg.value.startswith("rtpu_")):
                    out[arg.value] = os.path.relpath(path, pkg_dir)
    return out


def readme_metric_names(readme_path: str) -> Set[str]:
    try:
        with open(readme_path) as f:
            return set(_README_NAME_RE.findall(f.read()))
    except OSError:
        return set()


def check(repo_root: str = None) -> List[str]:
    """Returns a list of problems (empty = clean)."""
    root = repo_root or _repo_root()
    defined = collect_defined_metrics(os.path.join(root, "ray_tpu"))
    documented = readme_metric_names(os.path.join(root, "README.md"))
    problems: List[str] = []
    if not defined:
        problems.append("no telemetry.define() metric names found under "
                        "ray_tpu/ — the scanner is broken")
    for name, where in sorted(defined.items()):
        if not _NAME_RE.match(name):
            problems.append(
                f"{name} ({where}): violates the Prometheus naming "
                "grammar / rtpu_ lower-snake-case convention")
        if name not in documented:
            problems.append(
                f"{name} ({where}): not documented in the README.md "
                "Observability metric registry")
    for name in sorted(documented - set(defined)):
        problems.append(
            f"{name}: listed in the README registry but no "
            "telemetry.define() in ray_tpu/ registers it")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"metric-lint: {p}", file=sys.stderr)
    if problems:
        print(f"metric-lint: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print("metric-lint: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
