"""Observability-registry lint: every runtime metric the code defines
must be a valid Prometheus name AND documented in README.md's
Observability registry; every cluster-event label and span-name prefix
must appear in the README's event & span registry — new instrumentation
(including the ``debug/*`` events) can't ship undocumented.

Wired in as a tier-1 test (``tests/test_metric_lint.py``); also runnable
standalone: ``python -m ray_tpu.scripts.check_metrics``.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

# Prometheus metric-name grammar (https://prometheus.io/docs/concepts/
# data_model/) narrowed to this repo's convention: rtpu_ prefix,
# lower-snake-case. `_bucket`/`_sum`/`_count`/`_total` suffixes are part
# of the name as defined.
_NAME_RE = re.compile(r"^rtpu_[a-z][a-z0-9_]*$")
_README_NAME_RE = re.compile(r"`(rtpu_[A-Za-z0-9_:]+)`")

# Cluster-event labels (UPPER_SNAKE) and span-name prefixes
# (``lower_snake::``), validated against the README's
# "Cluster event & span registry" section only — scanning the whole
# README would catch unrelated backticked identifiers.
_LABEL_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")
_SPAN_PREFIX_RE = re.compile(r"^[a-z][a-z0-9_]*::$")
_README_LABEL_RE = re.compile(r"`([A-Z][A-Z0-9_]+)`")
_README_SPAN_RE = re.compile(r"`([a-z][a-z0-9_]*::)")
_REGISTRY_HEADING = "### Cluster event & span registry"


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def collect_defined_metrics(pkg_dir: str,
                            files=None) -> Dict[str, str]:
    """All metric names registered via ``telemetry.define(kind, name,
    ...)`` anywhere under the package, mapped to the defining file."""
    out: Dict[str, str] = {}
    for rel, tree in (files if files is not None
                      else _walk_files(pkg_dir)):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name != "define" or len(node.args) < 2:
                continue
            arg = node.args[1]
            if (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value.startswith("rtpu_")):
                out[arg.value] = rel
    return out


def readme_metric_names(readme_path: str) -> Set[str]:
    try:
        with open(readme_path) as f:
            return set(_README_NAME_RE.findall(f.read()))
    except OSError:
        return set()


_REGISTRY_ROW_RE = re.compile(
    r"^\|\s*`(rtpu_[a-z0-9_]+)`\s*\|\s*(\w+)\s*\|", re.MULTILINE)
_REGISTRY_LABEL_ROW_RE = re.compile(
    r"^\|\s*`(rtpu_[a-z0-9_]+)`\s*\|\s*\w+\s*\|\s*([^|]*)\|", re.MULTILINE)
_LABEL_NAME_RE = re.compile(r"`([a-z][a-z0-9_]*)`")
_TAG_KEY_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def readme_registry_rows(readme_path: str) -> List[Tuple[str, str]]:
    """Every (metric, declared type) registry-table row IN ORDER,
    duplicates included — two rows for one metric would silently shadow
    each other in the dict-shaped type/label views. Empty when the
    README has no such table (the name-presence check still applies)."""
    try:
        with open(readme_path) as f:
            text = f.read()
    except OSError:
        return []
    return _REGISTRY_ROW_RE.findall(text)


def readme_registry_types(readme_path: str) -> Dict[str, str]:
    """Metric name -> declared type (counter/gauge/histogram)."""
    return dict(readme_registry_rows(readme_path))


def collect_defined_metric_kinds(pkg_dir: str,
                                 files=None) -> Dict[str, Tuple[str, str]]:
    """Metric name -> (kind, file) for every ``telemetry.define(kind,
    name, ...)`` with literal kind and name."""
    out: Dict[str, Tuple[str, str]] = {}
    for rel, tree in (files if files is not None
                      else _walk_files(pkg_dir)):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name != "define" or len(node.args) < 2:
                continue
            kind_arg, name_arg = node.args[0], node.args[1]
            if (isinstance(kind_arg, ast.Constant)
                    and isinstance(kind_arg.value, str)
                    and isinstance(name_arg, ast.Constant)
                    and isinstance(name_arg.value, str)
                    and name_arg.value.startswith("rtpu_")):
                out[name_arg.value] = (kind_arg.value, rel)
    return out


_ANY_LABEL_TOKEN_RE = re.compile(r"`([^`]+)`")


def readme_registry_labels(readme_path: str) -> Dict[str, Set[str]]:
    """Metric name -> documented label set from the registry table's
    labels column (``—`` rows map to the empty set)."""
    try:
        with open(readme_path) as f:
            text = f.read()
    except OSError:
        return {}
    return {name: set(_LABEL_NAME_RE.findall(cell))
            for name, cell in _REGISTRY_LABEL_ROW_RE.findall(text)}


def readme_registry_label_cells(readme_path: str) -> List[Tuple[str, str]]:
    """(metric name, RAW labels-column cell) per registry row — for the
    label-naming lint, which must see malformed tokens that the
    well-formed-only ``_LABEL_NAME_RE`` extraction would drop."""
    try:
        with open(readme_path) as f:
            text = f.read()
    except OSError:
        return []
    return _REGISTRY_LABEL_ROW_RE.findall(text)


def collect_used_tag_keys(pkg_dir: str,
                          files=None) -> Dict[str, Dict[str, str]]:
    """Metric name -> {tag key -> file} for every literal ``tags=(("k",
    v), ...)`` passed to ``counter_inc``/``gauge_set``/``hist_observe``/
    ``digest_observe``/``digest_series`` whose metric argument is a name
    bound by ``X = telemetry.define(kind, "rtpu_...", ...)``. Dynamic
    tag expressions are skipped — the lint only judges what it can read
    statically."""
    files = list(files if files is not None else _walk_files(pkg_dir))
    # pass 1: variable name -> metric name (module-scope define binds)
    var_to_metric: Dict[str, str] = {}
    for _rel, tree in files:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            fn = node.value.func
            fname = (fn.attr if isinstance(fn, ast.Attribute)
                     else fn.id if isinstance(fn, ast.Name) else None)
            if fname != "define" or len(node.value.args) < 2:
                continue
            arg = node.value.args[1]
            if (isinstance(arg, ast.Constant) and isinstance(arg.value, str)
                    and arg.value.startswith("rtpu_")):
                var_to_metric[node.targets[0].id] = arg.value
    # pass 2: record-site tag keys
    out: Dict[str, Dict[str, str]] = {}
    for rel, tree in files:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            fname = (fn.attr if isinstance(fn, ast.Attribute)
                     else fn.id if isinstance(fn, ast.Name) else None)
            if fname not in ("counter_inc", "gauge_set", "hist_observe",
                             "digest_observe", "digest_series"):
                continue
            metric_arg = node.args[0]
            var = (metric_arg.attr if isinstance(metric_arg, ast.Attribute)
                   else metric_arg.id if isinstance(metric_arg, ast.Name)
                   else None)
            metric = var_to_metric.get(var or "")
            if metric is None:
                continue
            # digest_series prebinds (metric, tags) — the hot-path
            # digest_record sites carry no tags of their own, so the
            # prebind is where those series' keys are declared
            tag_pos = 1 if fname == "digest_series" else 2
            tags_node = None
            if len(node.args) > tag_pos:
                tags_node = node.args[tag_pos]
            for kw in node.keywords:
                if kw.arg == "tags":
                    tags_node = kw.value
            if not isinstance(tags_node, (ast.Tuple, ast.List)):
                continue
            for pair in tags_node.elts:
                if not (isinstance(pair, (ast.Tuple, ast.List))
                        and pair.elts
                        and isinstance(pair.elts[0], ast.Constant)
                        and isinstance(pair.elts[0].value, str)):
                    continue
                out.setdefault(metric, {})[pair.elts[0].value] = rel
    return out


def _walk_files(pkg_dir: str):
    for dirpath, _dirs, files in os.walk(pkg_dir):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            try:
                with open(path) as f:
                    tree = ast.parse(f.read(), filename=path)
            except (SyntaxError, OSError):
                continue
            yield os.path.relpath(path, pkg_dir), tree


def collect_event_labels(pkg_dir: str, files=None) -> Dict[str, str]:
    """Labels of every structured cluster event emitted through an
    EventLogger (``<x>.events.info/warning/error("LABEL", ...)`` and
    ``<x>.events.emit(sev, "LABEL", ...)``), mapped to the file."""
    out: Dict[str, str] = {}
    for rel, tree in (files if files is not None
                      else _walk_files(pkg_dir)):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Attribute)
                    and fn.value.attr == "events"):
                continue
            if fn.attr in ("info", "warning", "error"):
                arg_idx = 0
            elif fn.attr == "emit":
                arg_idx = 1
            else:
                continue
            if len(node.args) <= arg_idx:
                continue
            arg = node.args[arg_idx]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out[arg.value] = rel
    return out


def collect_span_prefixes(pkg_dir: str, files=None) -> Dict[str, str]:
    """Span-name prefixes (``xxx::``) appearing as string constants in
    the name argument of ``start_span``/``begin_span`` calls."""
    out: Dict[str, str] = {}
    for rel, tree in (files if files is not None
                      else _walk_files(pkg_dir)):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name not in ("start_span", "begin_span"):
                continue
            for sub in ast.walk(node.args[0]):
                if (isinstance(sub, ast.Constant)
                        and isinstance(sub.value, str)
                        and _SPAN_PREFIX_RE.match(sub.value)):
                    out[sub.value] = rel
    return out


def readme_event_registry(readme_path: str) -> Tuple[Set[str], Set[str]]:
    """(labels, span prefixes) documented in the README's
    "Cluster event & span registry" section."""
    try:
        with open(readme_path) as f:
            text = f.read()
    except OSError:
        return set(), set()
    start = text.find(_REGISTRY_HEADING)
    if start < 0:
        return set(), set()
    body = text[start + len(_REGISTRY_HEADING):]
    # section ends at the next heading of any level
    end = re.search(r"\n#{2,3} ", body)
    if end:
        body = body[:end.start()]
    return (set(_README_LABEL_RE.findall(body)),
            set(_README_SPAN_RE.findall(body)))


def check(repo_root: str = None) -> List[str]:
    """Returns a list of problems (empty = clean)."""
    root = repo_root or _repo_root()
    # one walk+parse of the package, shared by all three collectors
    files = list(_walk_files(os.path.join(root, "ray_tpu")))
    defined = collect_defined_metrics(os.path.join(root, "ray_tpu"),
                                      files)
    documented = readme_metric_names(os.path.join(root, "README.md"))
    problems: List[str] = []
    if not defined:
        problems.append("no telemetry.define() metric names found under "
                        "ray_tpu/ — the scanner is broken")
    for name, where in sorted(defined.items()):
        if not _NAME_RE.match(name):
            problems.append(
                f"{name} ({where}): violates the Prometheus naming "
                "grammar / rtpu_ lower-snake-case convention")
        if name not in documented:
            problems.append(
                f"{name} ({where}): not documented in the README.md "
                "Observability metric registry")
    for name in sorted(documented - set(defined)):
        problems.append(
            f"{name}: listed in the README registry but no "
            "telemetry.define() in ray_tpu/ registers it")
    # type column of the registry table must match the define() kind
    # (a histogram documented as a counter misleads every dashboard),
    # and the kind itself must be one the telemetry core implements —
    # a typo'd kind would otherwise record nothing, silently
    kinds = collect_defined_metric_kinds(os.path.join(root, "ray_tpu"),
                                         files)
    rows = readme_registry_rows(os.path.join(root, "README.md"))
    row_types = dict(rows)
    valid_kinds = ("counter", "gauge", "histogram", "digest")
    for name, (kind, where) in sorted(kinds.items()):
        if kind not in valid_kinds:
            problems.append(
                f"{name} ({where}): defined with unknown kind "
                f"{kind!r} (valid: {', '.join(valid_kinds)})")
        doc_type = row_types.get(name)
        if doc_type is not None and doc_type != kind:
            problems.append(
                f"{name} ({where}): defined as {kind} but the README "
                f"registry row says {doc_type}")
    # duplicate registry rows: the dict-shaped views keep only the LAST
    # row per metric, so a duplicate would silently make the type/label
    # lints judge against the wrong declaration
    seen_rows: Set[str] = set()
    for name, _type in rows:
        if name in seen_rows:
            problems.append(
                f"{name}: appears in more than one README registry row")
        seen_rows.add(name)
    # labels column: every tag key a record site attaches (statically
    # readable literal tuples) must be declared for that metric — an
    # undeclared label is invisible cardinality no dashboard knows about
    doc_labels = readme_registry_labels(os.path.join(root, "README.md"))
    # naming lint over the RAW label cells: the doc_labels extraction
    # above only keeps well-formed tokens, so a malformed declared
    # label (`node-id`, `nodeID`) would silently vanish from it
    for name, cell in readme_registry_label_cells(
            os.path.join(root, "README.md")):
        for tok in _ANY_LABEL_TOKEN_RE.findall(cell):
            if not _TAG_KEY_RE.match(tok):
                problems.append(
                    f"{name}: README registry declares label {tok!r}, "
                    "which violates the lower_snake label naming "
                    "convention")
    used_tags = collect_used_tag_keys(os.path.join(root, "ray_tpu"),
                                      files)
    for name, keys in sorted(used_tags.items()):
        declared = doc_labels.get(name)
        for key, where in sorted(keys.items()):
            if not _TAG_KEY_RE.match(key):
                problems.append(
                    f"{name} ({where}): tag key {key!r} violates the "
                    "lower_snake label naming convention")
            if declared is not None and key not in declared:
                problems.append(
                    f"{name} ({where}): records tag {key!r} but the "
                    "README registry row does not declare that label")
    problems += check_events(root, files)
    problems += check_bundle_sections(root, files)
    return problems


def check_bundle_sections(root: str, files=None) -> List[str]:
    """Debug-bundle registry lint (both directions, like the config-knob
    registry): every name in ``debug_bundle.BUNDLE_SECTIONS`` (the
    manifest's section list) must have a ``_capture_<name>`` function
    AND a ``_CAPTURERS`` dispatch entry, and every capturer must be
    listed — a new observability surface can't silently miss the
    bundle, and a dead section can't linger in the manifest schema."""
    pkg = os.path.join(root, "ray_tpu")
    if files is None:
        files = list(_walk_files(pkg))
    tree = None
    for rel, t in files:
        if rel.replace(os.sep, "/") == "_private/debug_bundle.py":
            tree = t
            break
    if tree is None:
        return ["_private/debug_bundle.py not found — the bundle "
                "section lint has nothing to check"]
    sections: List[str] = []
    capturers: Set[str] = set()
    dispatch: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            target = node.targets[0].id
            if target == "BUNDLE_SECTIONS" and isinstance(
                    node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if (isinstance(elt, ast.Constant)
                            and isinstance(elt.value, str)):
                        sections.append(elt.value)
            elif target == "_CAPTURERS" and isinstance(node.value,
                                                       ast.Dict):
                for k in node.value.keys:
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        dispatch.add(k.value)
        elif (isinstance(node, ast.FunctionDef)
                and node.name.startswith("_capture_")):
            capturers.add(node.name[len("_capture_"):])
    problems: List[str] = []
    if not sections:
        problems.append("debug_bundle.BUNDLE_SECTIONS is empty or not a "
                        "literal tuple — the bundle scanner is broken")
    dupes = {s for s in sections if sections.count(s) > 1}
    for s in sorted(dupes):
        problems.append(f"bundle section {s!r}: listed more than once "
                        "in BUNDLE_SECTIONS")
    listed = set(sections)
    for s in sorted(listed - capturers):
        problems.append(f"bundle section {s!r}: in BUNDLE_SECTIONS but "
                        "no _capture_ function captures it")
    for s in sorted(capturers - listed):
        problems.append(f"bundle capturer _capture_{s}: not listed in "
                        "BUNDLE_SECTIONS (the manifest would omit it)")
    for s in sorted(listed - dispatch):
        problems.append(f"bundle section {s!r}: missing from the "
                        "_CAPTURERS dispatch table")
    for s in sorted(dispatch - listed):
        problems.append(f"bundle dispatch entry {s!r}: not listed in "
                        "BUNDLE_SECTIONS")
    return problems


def check_events(root: str, files=None) -> List[str]:
    """Event-label + span-name half of the lint."""
    pkg = os.path.join(root, "ray_tpu")
    if files is None:
        files = list(_walk_files(pkg))
    labels = collect_event_labels(pkg, files)
    spans = collect_span_prefixes(pkg, files)
    doc_labels, doc_spans = readme_event_registry(
        os.path.join(root, "README.md"))
    problems: List[str] = []
    if not labels:
        problems.append("no EventLogger emit sites found under ray_tpu/ "
                        "— the event scanner is broken")
    for label, where in sorted(labels.items()):
        if not _LABEL_RE.match(label):
            problems.append(
                f"{label} ({where}): event labels must be UPPER_SNAKE")
        if label not in doc_labels:
            problems.append(
                f"{label} ({where}): not documented in the README.md "
                "cluster event & span registry")
    for label in sorted(doc_labels - set(labels)):
        problems.append(
            f"{label}: in the README event registry but never emitted "
            "under ray_tpu/")
    for prefix, where in sorted(spans.items()):
        if prefix not in doc_spans:
            problems.append(
                f"span prefix {prefix!r} ({where}): not documented in "
                "the README.md cluster event & span registry")
    for prefix in sorted(doc_spans - set(spans)):
        problems.append(
            f"span prefix {prefix!r}: in the README registry but no "
            "start_span/begin_span under ray_tpu/ uses it")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"metric-lint: {p}", file=sys.stderr)
    if problems:
        print(f"metric-lint: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print("metric-lint: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
