"""Task and actor API: the ``@remote`` decorator and handles.

Equivalent role to the reference's ``RemoteFunction``
(``python/ray/remote_function.py:40``), ``ActorClass``/``ActorHandle``
(``python/ray/actor.py:384/1025``) and the ``ray.remote`` decorator
(``python/ray/_private/worker.py:3027``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ._private import context
from ._private import locksan
from ._private import protocol as P
from ._private import serialization as ser
from ._private.client import function_id_of
from ._private.config import CONFIG
from ._private.ids import ActorID, ObjectID
from ._private.object_ref import ObjectRef

_DEFAULT_TASK_CPUS = 1.0
# Alive actors hold NO cpu by default (reference semantics: the implicit
# 1 CPU applies to the creation task only — ``actor.py:384`` "num_cpus:
# ... default 1 for creation, 0 for running"). A lifetime CPU charge per
# actor starves task dispatch on small nodes; explicit num_cpus= still
# reserves for the actor's lifetime.
_DEFAULT_ACTOR_CPUS = 0.0


def _norm_num_returns(n) -> int:
    """\"streaming\"/\"dynamic\" -> -1 (dynamic returns via
    ObjectRefGenerator; reference: ``num_returns=\"streaming\"``)."""
    if n in ("streaming", "dynamic"):
        return -1
    return int(n)


def _build_resources(opts: Dict[str, Any], default_cpus: float) -> Dict[str, float]:
    res: Dict[str, float] = {}
    num_cpus = opts.get("num_cpus")
    res["CPU"] = float(default_cpus if num_cpus is None else num_cpus)
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus"):  # accepted for API familiarity; maps to TPU
        res["TPU"] = float(opts["num_gpus"])
    if opts.get("memory"):
        res["memory"] = float(opts["memory"])
    for k, v in (opts.get("resources") or {}).items():
        res[k] = float(v)
    for k, v in res.items():
        if v > 1 and not float(v).is_integer():
            # fractional shares are only meaningful within one unit —
            # 1.5 TPUs cannot map to exclusive chip slots (reference:
            # fractional quantities must be <= 1)
            raise ValueError(
                f"resource quantities over 1 must be whole numbers, "
                f"got {k}={v}")
    out = {k: v for k, v in res.items() if v}
    if num_cpus is not None and "CPU" not in out:
        # an EXPLICIT num_cpus=0 must survive into the spec: it opts the
        # actor out of the implicit 1-CPU creation charge (reference:
        # "default 1 for creation, 0 for running" — explicit 0 means
        # 0/0). Without it a 0-CPU helper actor (e.g. a collective
        # group's coordinator) can never start on a saturated node,
        # deadlocking the very ranks that wait on it while holding
        # every CPU.
        out["CPU"] = 0.0
    return out


def _resolve_runtime_env(opts, client):
    """Merge the job-level runtime env (init(runtime_env=...)) with the
    per-task/actor one; env_vars merge key-wise, other keys override
    (reference: runtime-env inheritance semantics)."""
    from ._private import runtime_env as renv
    job_env = getattr(client, "job_runtime_env", None)
    task_env = renv.validate(opts.get("runtime_env"))
    if not job_env:
        return task_env
    if not task_env:
        return job_env
    merged = {**job_env, **task_env}
    if "env_vars" in job_env or "env_vars" in task_env:
        merged["env_vars"] = {**job_env.get("env_vars", {}),
                              **task_env.get("env_vars", {})}
    return merged


class RemoteFunction:
    """A function callable via ``.remote()`` (reference:
    ``remote_function.py:40``; submission path ``_remote`` :257)."""

    def __init__(self, fn, **options):
        self._fn = fn
        self._options = options
        self._name = options.get("name") or getattr(fn, "__qualname__",
                                                    str(fn))
        self._blob: Optional[bytes] = None
        self._function_id: Optional[bytes] = None
        self._lock = locksan.lock("api.remote_fn")

    def options(self, **options) -> "RemoteFunction":
        merged = {**self._options, **options}
        rf = RemoteFunction(self._fn, **merged)
        rf._blob = self._blob
        rf._function_id = self._function_id
        return rf

    def _ensure_exported(self, client) -> bytes:
        with self._lock:
            if self._function_id is None:
                self._blob = ser.dumps_function(self._fn)
                self._function_id = function_id_of(self._blob)
        client.ensure_function(self._function_id, lambda: self._blob)
        return self._function_id

    def remote(self, *args, **kwargs):
        client = context.require_client()
        fid = self._ensure_exported(client)
        opts = self._options
        num_returns = _norm_num_returns(opts.get("num_returns", 1))
        refs = client.submit_task(
            function_id=fid,
            name=self._name,
            args=args, kwargs=kwargs,
            num_returns=num_returns,
            resources=_build_resources(opts, _DEFAULT_TASK_CPUS),
            max_retries=opts.get("max_retries",
                                 CONFIG.task_max_retries_default),
            scheduling_strategy=opts.get("scheduling_strategy"),
            retry_exceptions=opts.get("retry_exceptions", False),
            runtime_env=_resolve_runtime_env(opts, client))
        if num_returns == -1:
            return refs                 # ObjectRefGenerator
        if num_returns == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Lazy DAG node instead of immediate submission (reference:
        ``dag/function_node.py``)."""
        from .dag import FunctionNode
        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._name} cannot be called directly; "
            f"use {self._name}.remote(...)")

    def __reduce__(self):
        # Exported already: ship the cached blob (plain-pickle-friendly,
        # keeps one function id across processes). NOT exported yet —
        # which includes mid-export, when a recursive function's closure
        # reaches back to itself — pickle the RAW function inside the
        # ENCLOSING dump: a nested dump here would deadlock on
        # self._lock and then recurse forever, while the enclosing
        # pickler's memo handles the closure cycle fine. The rebuilt
        # instance re-exports lazily on first .remote().
        blob = self._blob
        if blob is not None:
            return (_rebuild_remote_function_blob,
                    (blob, self._options))
        return (_rebuild_remote_function, (self._fn, self._options))


def _rebuild_remote_function(fn, options: dict) -> "RemoteFunction":
    return RemoteFunction(fn, **options)


def _rebuild_remote_function_blob(blob: bytes,
                                  options: dict) -> "RemoteFunction":
    rf = RemoteFunction(ser.loads_function(blob), **options)
    rf._blob = blob
    rf._function_id = function_id_of(blob)
    return rf


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str):
        self._handle = handle
        self._method_name = method_name

    def options(self, **opts) -> "ActorMethod":
        m = ActorMethod(self._handle, self._method_name)
        m._opts = opts
        return m

    def bind(self, *args, **kwargs):
        """DAG node calling this method on the LIVE handle (reference:
        binding methods of an existing actor into a DAG)."""
        from .dag import FunctionNode
        return FunctionNode(self, args, kwargs)

    @property
    def _name(self):
        return f"{self._handle._class_name}.{self._method_name}"

    def remote(self, *args, **kwargs):
        client = context.require_client()
        # precedence: .options() > @method defaults on the class
        opts = {**self._handle._method_opts.get(self._method_name, {}),
                **getattr(self, "_opts", {})}
        num_returns = _norm_num_returns(opts.get("num_returns", 1))
        refs = client.submit_actor_task(
            actor_id=self._handle._actor_id,
            method_name=self._method_name,
            args=args, kwargs=kwargs,
            num_returns=num_returns,
            seq_no=self._handle._next_seq(),
            name=f"{self._handle._class_name}.{self._method_name}")
        if num_returns == -1:
            return refs                 # ObjectRefGenerator
        if num_returns == 1:
            return refs[0]
        return refs


def _rebuild_handle(actor_id_bytes: bytes, class_name: str,
                    method_opts: Optional[dict] = None):
    return ActorHandle(ActorID(actor_id_bytes), class_name, method_opts)


class ActorHandle:
    """Reference to a live actor; methods via attribute access (reference:
    ``actor.py:1025``). Picklable: reconstructs against the local client."""

    def __init__(self, actor_id: ActorID, class_name: str,
                 method_opts: Optional[Dict[str, dict]] = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_opts = method_opts or {}
        self._seq = 0
        self._seq_lock = locksan.lock("api.actor_seq")

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def _next_seq(self) -> int:
        with self._seq_lock:
            self._seq += 1
            return self._seq

    def __getattr__(self, name: str) -> ActorMethod:
        # Underscore attributes fail lookup (pickle/inspect/duck-typing
        # probes expect AttributeError) — except the framework's own
        # actor hooks (_rtpu_*), which are remote-callable.
        if name.startswith("_") and not name.startswith("_rtpu_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_id.binary(), self._class_name,
                                  self._method_opts))


class ActorClass:
    """Produced by ``@remote`` on a class (reference: ``actor.py:384``)."""

    def __init__(self, cls, **options):
        self._cls = cls
        self._options = options
        self._blob: Optional[bytes] = None
        self._lock = locksan.lock("api.actor_class")

    def options(self, **options) -> "ActorClass":
        merged = {**self._options, **options}
        ac = ActorClass(self._cls, **merged)
        ac._blob = self._blob
        return ac

    def remote(self, *args, **kwargs) -> ActorHandle:
        client = context.require_client()
        with self._lock:
            if self._blob is None:
                self._blob = ser.dumps_function(self._cls)
        opts = self._options
        actor_id = ActorID.from_random()
        packed, pkw = client.pack_args(args, kwargs)
        creation_return = ObjectID.for_put(client.worker_id)
        spec = P.ActorSpec(
            actor_id=actor_id,
            job_id=client.job_id,
            name=self._cls.__name__,
            registered_name=opts.get("name"),
            namespace=opts.get("namespace") or context.active_namespace(),
            class_blob=self._blob,
            args=packed, kwargs=pkw,
            resources=_build_resources(opts, _DEFAULT_ACTOR_CPUS),
            max_restarts=opts.get("max_restarts",
                                  CONFIG.actor_max_restarts_default),
            max_concurrency=opts.get("max_concurrency", 1),
            is_async=self._detect_async(),
            lifetime=opts.get("lifetime"),
            scheduling_strategy=opts.get("scheduling_strategy"),
            creation_return_id=creation_return,
            runtime_env=_resolve_runtime_env(opts, client))
        client.create_actor(spec)
        handle = ActorHandle(actor_id, self._cls.__name__,
                             self._method_options())
        handle._ready_ref = ObjectRef(creation_return)
        return handle

    def _method_options(self) -> Dict[str, dict]:
        """Collect ``@method(...)`` defaults declared on the class."""
        out: Dict[str, dict] = {}
        for name in dir(self._cls):
            member = getattr(self._cls, name, None)
            opts = getattr(member, "_rtpu_method_opts", None)
            if opts:
                out[name] = opts
        return out

    def _detect_async(self) -> bool:
        import inspect
        for name, member in inspect.getmembers(self._cls):
            if not name.startswith("__") and inspect.iscoroutinefunction(member):
                return True
        return False

    def bind(self, *args, **kwargs):
        """Lazy actor-creation DAG node (reference: ``dag/class_node.py``)."""
        from .dag import ClassNode
        return ClassNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__} cannot be instantiated "
            f"directly; use {self._cls.__name__}.remote(...)")

    def __reduce__(self):
        with self._lock:
            if self._blob is None:
                self._blob = ser.dumps_function(self._cls)
        return (_rebuild_actor_class, (self._blob, self._options))


def _rebuild_actor_class(blob: bytes, options: dict) -> "ActorClass":
    ac = ActorClass(ser.loads_function(blob), **options)
    ac._blob = blob
    return ac


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=..., num_tpus=..., ...)``."""
    if len(args) == 1 and not kwargs and callable(args[0]):
        target = args[0]
        if isinstance(target, type):
            return ActorClass(target)
        return RemoteFunction(target)
    if args:
        raise TypeError("remote() takes keyword options only")

    def decorator(target):
        if isinstance(target, type):
            return ActorClass(target, **kwargs)
        return RemoteFunction(target, **kwargs)

    return decorator


def method(**opts):
    """Decorator for actor methods carrying default options (reference:
    ``ray.method``)."""

    def decorator(fn):
        fn._rtpu_method_opts = opts
        return fn

    return decorator
