"""Device-mesh construction with named parallelism axes.

The reference expresses parallelism as process groups created per strategy
(DDP ``train/torch/config.py:63``; NCCL groups
``util/collective/collective.py:120``). TPU-native design: one global
`jax.sharding.Mesh` whose named axes carry every strategy at once —

  ``dp``   data parallel (gradient psum)
  ``fsdp`` sharded data parallel (ZeRO: params/optimizer sharded, gathered
           per-layer; maps to the reference's FSDP/DeepSpeed passthrough,
           ``train/lightning/_lightning_utils.py:84,127``)
  ``tp``   tensor parallel (megatron-style column/row sharding)
  ``sp``   sequence/context parallel (ring attention — absent from the
           reference, first-class here per SURVEY §5)
  ``pp``   pipeline parallel (stage dimension)
  ``ep``   expert parallel (MoE)

Mesh axis *order* matters on TPU: the innermost (last) axes should map to
ICI-adjacent devices. We order axes (pp, dp, fsdp, ep, sp, tp) so that
tp/sp — the chatty collectives — land on contiguous device neighbourhoods.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_PP = "pp"
AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_EP = "ep"
AXIS_SP = "sp"
AXIS_TP = "tp"

# Innermost-last ordering: tp gets the fastest ICI links.
AXIS_ORDER: Tuple[str, ...] = (AXIS_PP, AXIS_DP, AXIS_FSDP, AXIS_EP,
                               AXIS_SP, AXIS_TP)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape. ``-1`` on at most one axis means "absorb the
    remaining devices" (like a reshape wildcard)."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = {f.name: getattr(self, f.name)
                 for f in dataclasses.fields(self)}
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wild}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh spec {sizes} covers {fixed} devices, have "
                f"{n_devices}")
        return MeshSpec(**sizes)

    def axis_sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, name) for name in AXIS_ORDER)

    @property
    def total(self) -> int:
        if any(s == -1 for s in self.axis_sizes()):
            raise ValueError(
                "MeshSpec has an unresolved -1 axis; call resolve(n) first")
        return math.prod(self.axis_sizes())


def mesh_shape_for(n_devices: int,
                   tp: int = 1,
                   sp: int = 1,
                   pp: int = 1,
                   ep: int = 1,
                   fsdp: int = 1) -> MeshSpec:
    """Convenience: everything not given goes to dp."""
    return MeshSpec(dp=-1, fsdp=fsdp, tp=tp, sp=sp, pp=pp,
                    ep=ep).resolve(n_devices)


def build_mesh(spec: Optional[MeshSpec] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a `jax.sharding.Mesh` with the canonical axis names.

    Uses `jax.experimental.mesh_utils.create_device_mesh` when the device
    count allows so physical ICI adjacency is respected on real TPU
    topologies; falls back to a plain reshape (CPU / virtual devices).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    spec = (spec or MeshSpec()).resolve(len(devices))
    shape = spec.axis_sizes()
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices, allow_split_physical_axes=True)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)
