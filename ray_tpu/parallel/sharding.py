"""Logical-axis sharding rules.

Model code names array dimensions with *logical* axes ("batch", "embed",
"mlp", …). A `ShardingRules` table maps logical names → mesh axes; pjit
shardings are derived from it. This is the GSPMD-native equivalent of the
reference's per-strategy wrappers (DDP wrap `train_loop_utils.py:74`,
FSDP/DeepSpeed strategies `_lightning_utils.py:84,127`): changing the
parallelism is a rules/mesh change, never a model change.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import (AXIS_DP, AXIS_EP, AXIS_FSDP, AXIS_PP, AXIS_SP,
                   AXIS_TP)

LogicalAxis = Optional[str]
MeshAxes = Union[None, str, Tuple[str, ...]]


class ShardingRules(dict):
    """Mapping logical axis name → mesh axis (or tuple of mesh axes)."""

    def mesh_axes(self, logical: LogicalAxis) -> MeshAxes:
        if logical is None:
            return None
        return self.get(logical)

    def spec(self, *logical_axes: LogicalAxis) -> P:
        return P(*(self.mesh_axes(a) for a in logical_axes))


# The canonical recipe (scaling-book style): activation batch over
# (dp, fsdp); *weight* embed dim over fsdp (ZeRO gather per layer);
# heads/mlp over tp (megatron); sequence over sp (ring attention);
# experts over ep. Activation dims get their own logical names — a single
# PartitionSpec may use each mesh axis only once, so "act_batch" already
# consuming fsdp means "act_embed" must not.
DEFAULT_RULES = ShardingRules({
    # activations
    "act_batch": (AXIS_DP, AXIS_FSDP),
    "act_seq": AXIS_SP,
    "act_embed": None,
    "act_heads": AXIS_TP,
    "act_kv_heads": AXIS_TP,
    "act_mlp": AXIS_TP,
    "act_vocab": AXIS_TP,
    "head_dim": None,
    # weights
    "embed": AXIS_FSDP,
    "heads": AXIS_TP,
    "kv_heads": AXIS_TP,
    "mlp": AXIS_TP,
    "vocab": AXIS_TP,
    "expert": AXIS_EP,
    "layers": None,
    "stage": AXIS_PP,
})


def logical_spec_to_mesh_spec(rules: ShardingRules,
                              logical: Sequence[LogicalAxis]) -> P:
    return rules.spec(*logical)


def logical_sharding(mesh: Mesh, rules: ShardingRules,
                     logical: Sequence[LogicalAxis]) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(*logical))


def with_logical_constraint(x: jax.Array,
                            *logical_axes: LogicalAxis,
                            rules: Optional[ShardingRules] = None,
                            mesh: Optional[Mesh] = None) -> jax.Array:
    """`lax.with_sharding_constraint` by logical axis names.

    Inside ``jax.set_mesh`` (or jit traced under one) the mesh is implicit;
    otherwise pass it. No-op when no mesh is active (single-device eager
    paths, CPU tests).
    """
    rules = rules if rules is not None else DEFAULT_RULES
    spec = rules.spec(*logical_axes)
    if mesh is not None:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    # jax < 0.5 has no get_abstract_mesh; without it (and without an
    # explicit mesh) there is no way to name an implicit mesh — no-op,
    # matching the "no mesh is active" contract
    get_abstract = getattr(jax.sharding, "get_abstract_mesh", None)
    abstract = get_abstract() if get_abstract is not None else None
    if abstract is None or not abstract.axis_names:
        return x
    # Drop references to axes the active mesh doesn't carry.
    known = set(abstract.axis_names)

    def _filter(entry: MeshAxes) -> MeshAxes:
        if entry is None:
            return None
        if isinstance(entry, str):
            return entry if entry in known else None
        kept = tuple(a for a in entry if a in known)
        return kept or None

    spec = P(*(_filter(e) for e in spec))
    return jax.lax.with_sharding_constraint(x, spec)


def shard_params(params: Any, logical_tree: Any, mesh: Mesh,
                 rules: Optional[ShardingRules] = None) -> Any:
    """Device-put a param pytree according to a matching pytree of logical
    axis tuples (as produced by a model's ``param_logical_axes()``)."""
    rules = rules if rules is not None else DEFAULT_RULES

    def _put(x, logical):
        return jax.device_put(x, logical_sharding(mesh, rules, logical))

    return jax.tree_util.tree_map(_put, params, logical_tree,
                                  is_leaf=lambda x: x is None)


def sharding_tree(logical_tree: Any, mesh: Mesh,
                  rules: Optional[ShardingRules] = None) -> Any:
    """Pytree of NamedShardings matching a pytree of logical-axis tuples
    (for jit in_shardings/out_shardings)."""
    rules = rules if rules is not None else DEFAULT_RULES
    return jax.tree_util.tree_map(
        lambda logical: logical_sharding(mesh, rules, logical),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) or x is None)
