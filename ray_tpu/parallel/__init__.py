"""ray_tpu.parallel — device meshes, logical sharding rules, SPMD helpers.

This is the TPU-native replacement for the reference's process-group world
(`torch.distributed` rendezvous in ``train/torch/config.py:63`` and the
NCCL/Gloo groups of ``util/collective/collective.py``): instead of wiring
N single-device processes together with NCCL, we describe the whole slice
as one `jax.sharding.Mesh` with named axes (dp/fsdp/tp/sp/pp/ep) and let
XLA place collectives on ICI.
"""

from .mesh import (  # noqa: F401
    AXIS_DP,
    AXIS_EP,
    AXIS_FSDP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    MeshSpec,
    build_mesh,
    mesh_shape_for,
)
from .sharding import (  # noqa: F401
    DEFAULT_RULES,
    ShardingRules,
    logical_sharding,
    logical_spec_to_mesh_spec,
    shard_params,
    with_logical_constraint,
)
