"""Causal multi-head attention: jnp reference + Pallas TPU flash kernel.

The reference framework has no attention kernel of its own (it defers to
torch); on TPU the attention inner loop is the single hottest op of the
flagship models, so it gets a first-class FlashAttention-2 style Pallas
kernel: blocked online softmax in VMEM, fp32 accumulators, GQA-aware
block mapping, causal block skipping, and a custom VJP whose backward is
two more Pallas kernels (dq and dk/dv) driven by the saved logsumexp.

Shapes follow [batch, num_heads, seq, head_dim] ("BHSD"). GQA is
expressed as num_q_heads = G * num_kv_heads; the kernels map q-head h to
kv-head h // G in BlockSpec index maps, so no K/V replication ever
materializes.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128  # softmax running state is lane-replicated to this width

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


# ---------------------------------------------------------------------------
# Reference implementation (ground truth; CPU path)
# ---------------------------------------------------------------------------

def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        scale: Optional[float] = None) -> jax.Array:
    """Plain jnp attention with GQA. q: [B, H, S, D]; k/v: [B, Hk, S, D]."""
    *_, num_q_heads, q_len, head_dim = q.shape
    num_kv_heads = k.shape[-3]
    k_len = k.shape[-2]
    scale = scale if scale is not None else 1.0 / math.sqrt(head_dim)
    if num_q_heads != num_kv_heads:
        group = num_q_heads // num_kv_heads
        k = jnp.repeat(k, group, axis=-3)
        v = jnp.repeat(v, group, axis=-3)
    s = jnp.einsum("...hqd,...hkd->...hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        # Aligned to the end: query i attends keys j <= i + (k_len - q_len).
        qi = jax.lax.broadcasted_iota(jnp.int32, (q_len, k_len), 0)
        kj = jax.lax.broadcasted_iota(jnp.int32, (q_len, k_len), 1)
        s = jnp.where(kj <= qi + (k_len - q_len), s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("...hqk,...hkd->...hqd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _zero_padded_rows(x, block_start, length):
    """Zero rows of a loaded block that lie beyond the logical length.
    Out-of-bounds block reads return unspecified padding (NaN under the
    interpreter) and 0 * NaN = NaN would leak through the matmuls."""
    rows = block_start + jax.lax.broadcasted_iota(
        jnp.int32, (x.shape[0], 1), 0)
    return jnp.where(rows < length, x, 0.0)


def _tile_mask(qb, kb, *, block_q, block_k, q_len, k_len, causal):
    """Validity mask for the (qb, kb) tile: in-bounds rows/cols, plus the
    end-aligned causal constraint kj <= qi + (k_len - q_len) — matching
    ``attention_reference`` for q_len != k_len (decode-style calls)."""
    qi = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kj = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = (qi < q_len) & (kj < k_len)
    if causal:
        mask &= kj <= qi + (k_len - q_len)
    return mask


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k,
                q_len, k_len):
    qb = pl.program_id(2)
    kb = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # The (qb, kb) tile is dead under causal masking iff every key index
    # exceeds every (end-aligned) query index in it.
    live = (kb * block_k <= qb * block_q + block_q - 1 + k_len - q_len) \
        if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = _zero_padded_rows(k_ref[0, 0].astype(jnp.float32),
                              kb * block_k, k_len)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _tile_mask(qb, kb, block_q=block_q, block_k=block_k,
                          q_len=q_len, k_len=k_len, causal=causal)
        s = jnp.where(mask, s, NEG_INF)
        # Running state is lane-replicated [block_q, _LANES].
        m_prev = m_ref[:]
        s_max = jnp.max(s, axis=1, keepdims=True)          # [bq, 1]
        m_new = jnp.maximum(m_prev, s_max)                  # [bq, LANES]
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])                       # [bq, bk]
        # Fully-masked (padded) rows have m == NEG_INF and would exp to 1.
        p = jnp.where(mask, p, 0.0)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        v = _zero_padded_rows(v_ref[0, 0].astype(jnp.float32),
                              kb * block_k, k_len)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha[:, :1] + pv

    @pl.when(kb == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse = m_ref[:] + jnp.log(jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:]))
        lse_ref[0, 0] = lse.astype(jnp.float32)


def _fwd_pallas(q, k, v, *, scale, causal, block_q, block_k, interpret):
    batch, num_q_heads, q_len, head_dim = q.shape
    num_kv_heads, k_len = k.shape[1], k.shape[2]
    group = num_q_heads // num_kv_heads
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    nq = pl.cdiv(q_len, block_q)
    nk = pl.cdiv(k_len, block_k)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               q_len=q_len, k_len=k_len)
    out, lse = pl.pallas_call(
        kernel,
        grid=(batch, num_q_heads, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j: (b, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j: (b, h // group, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, _LANES),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((batch, num_q_heads, q_len, _LANES),
                                 jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Pallas backward kernels (FlashAttention-2 style, lse + delta residuals)
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale, causal, block_q, block_k, q_len,
                   k_len):
    qb = pl.program_id(2)
    kb = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    live = (kb * block_k <= qb * block_q + block_q - 1 + k_len - q_len) \
        if causal else True

    @pl.when(live)
    def _compute():
        q = _zero_padded_rows(q_ref[0, 0].astype(jnp.float32),
                              qb * block_q, q_len)
        k = _zero_padded_rows(k_ref[0, 0].astype(jnp.float32),
                              kb * block_k, k_len)
        v = _zero_padded_rows(v_ref[0, 0].astype(jnp.float32),
                              kb * block_k, k_len)
        do = _zero_padded_rows(do_ref[0, 0].astype(jnp.float32),
                               qb * block_q, q_len)
        lse = lse_ref[0, 0][:, :1]                          # [bq, 1]
        delta = _zero_padded_rows(delta_ref[0, 0], qb * block_q,
                                  q_len)[:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _tile_mask(qb, kb, block_q=block_q, block_k=block_k,
                          q_len=q_len, k_len=k_len, causal=causal)
        # Padded rows carry garbage lse; zero their probabilities exactly.
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == nk - 1)
    def _finalize():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, q_len, k_len):
    kb = pl.program_id(2)
    qb = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qb == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = (qb * block_q + block_q - 1 + k_len - q_len >= kb * block_k) \
        if causal else True

    @pl.when(live)
    def _compute():
        q = _zero_padded_rows(q_ref[0, 0].astype(jnp.float32),
                              qb * block_q, q_len)
        k = _zero_padded_rows(k_ref[0, 0].astype(jnp.float32),
                              kb * block_k, k_len)
        v = _zero_padded_rows(v_ref[0, 0].astype(jnp.float32),
                              kb * block_k, k_len)
        do = _zero_padded_rows(do_ref[0, 0].astype(jnp.float32),
                               qb * block_q, q_len)
        lse = lse_ref[0, 0][:, :1]
        delta = _zero_padded_rows(delta_ref[0, 0], qb * block_q,
                                  q_len)[:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _tile_mask(qb, kb, block_q=block_q, block_k=block_k,
                          q_len=q_len, k_len=k_len, causal=causal)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qb == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, out, lse, do, *, scale, causal, block_q, block_k,
                interpret, delta=None, keep_f32=False):
    batch, num_q_heads, q_len, head_dim = q.shape
    num_kv_heads, k_len = k.shape[1], k.shape[2]
    group = num_q_heads // num_kv_heads
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    nq = pl.cdiv(q_len, block_q)
    nk = pl.cdiv(k_len, block_k)

    if delta is None:
        # delta_i = rowsum(dO * O); cheap, fused by XLA.
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1)
    # Lane-replicate [B, H, S] row statistics to match the lse layout.
    delta = jnp.broadcast_to(delta[..., None],
                             (*delta.shape, _LANES))

    q_spec = pl.BlockSpec((1, 1, block_q, head_dim),
                          lambda b, h, i, j: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, head_dim),
                           lambda b, h, i, j: (b, h // group, j, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, _LANES),
                            lambda b, h, i, j: (b, h, i, 0))

    dq_dtype = jnp.float32 if keep_f32 else q.dtype
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          q_len=q_len, k_len=k_len),
        grid=(batch, num_q_heads, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, dq_dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: kv block is the outer grid axis, q blocks stream innermost.
    q_spec_i = pl.BlockSpec((1, 1, block_q, head_dim),
                            lambda b, h, j, i: (b, h, i, 0))
    kv_spec_i = pl.BlockSpec((1, 1, block_k, head_dim),
                             lambda b, h, j, i: (b, h // group, j, 0))
    row_spec_i = pl.BlockSpec((1, 1, block_q, _LANES),
                              lambda b, h, j, i: (b, h, i, 0))
    kv_out_spec = pl.BlockSpec((1, 1, block_k, head_dim),
                               lambda b, h, j, i: (b, h, j, 0))

    # Accumulated per q-head, then reduced over the GQA group outside.
    dkv_shape = (batch, num_q_heads, k_len, head_dim)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          q_len=q_len, k_len=k_len),
        grid=(batch, num_q_heads, nk, nq),
        in_specs=[q_spec_i, kv_spec_i, kv_spec_i, q_spec_i, row_spec_i,
                  row_spec_i],
        out_specs=[kv_out_spec, kv_out_spec],
        out_shape=[
            jax.ShapeDtypeStruct(dkv_shape, jnp.float32),
            jax.ShapeDtypeStruct(dkv_shape, jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, head_dim), jnp.float32),
                        pltpu.VMEM((block_k, head_dim), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    if group > 1:
        dk = dk.reshape(batch, num_kv_heads, group, k_len, head_dim)
        dk = dk.sum(axis=2)
        dv = dv.reshape(batch, num_kv_heads, group, k_len, head_dim)
        dv = dv.sum(axis=2)
    if keep_f32:
        return dq, dk, dv
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public flash attention with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False):
    """FlashAttention-2 on TPU (Pallas). [B, H, S, D]; GQA via Hk | H."""
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    scale_val = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, lse = _fwd_pallas(q, k, v, scale=scale_val, causal=causal,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    scale_val = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    dq, dk, dv = _bwd_pallas(q, k, v, out, lse, g, scale=scale_val,
                             causal=causal, block_q=block_q,
                             block_k=block_k, interpret=interpret)
    return dq, dk, dv


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

def dot_product_attention(q, k, v, causal: bool = True,
                          scale: Optional[float] = None,
                          impl: str = "auto",
                          block_q: int = DEFAULT_BLOCK_Q,
                          block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Attention entry point used by models.

    impl: "auto" (pallas on TPU, reference elsewhere), "pallas",
    "pallas_interpret" (kernel under the interpreter — CPU tests),
    "reference".
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "reference"
    if impl == "reference":
        return attention_reference(q, k, v, causal=causal, scale=scale)
    if impl == "pallas":
        return flash_attention(q, k, v, causal, scale, block_q, block_k,
                               False)
    if impl == "pallas_interpret":
        return flash_attention(q, k, v, causal, scale, block_q, block_k,
                               True)
    raise ValueError(f"unknown attention impl {impl!r}")
