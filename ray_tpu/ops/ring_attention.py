"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

The reference framework has no sequence/context parallelism at all
(SURVEY §5: no ring attention / Ulysses anywhere in the tree); here it is
first-class. Sequence is sharded over the mesh axis ``sp``; K/V blocks
circulate around the ring via `lax.ppermute` while each device keeps its
own Q shard, merging per-block softmax partials online (FlashAttention
accumulation across devices). Communication rides ICI neighbor links and
overlaps with the per-block attention compute.

Must be called *inside* `shard_map` (or an equivalently manual axis
context) with q/k/v already sharded over `axis_name` on the sequence
dimension. The backward pass runs the ring again, circulating dK/dV
accumulators along with the K/V blocks so a full cycle deposits them back
on their home shard.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .attention import (DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q, NEG_INF, _LANES,
                        _bwd_pallas, _fwd_pallas)

_FULL = 0   # attend to every key in the block
_DIAG = 1   # intra-shard causal (the step-0 diagonal block)


def _repeat_kv(k, group):
    return jnp.repeat(k, group, axis=-3) if group > 1 else k


def _partial_fwd_reference(q, k, v, scale, diag):
    """Blockwise attention partial → (out_f32, lse) in plain jnp."""
    group = q.shape[-3] // k.shape[-3]
    k, v = _repeat_kv(k, group), _repeat_kv(v, group)
    s = jnp.einsum("...hqd,...hkd->...hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if diag:
        q_len, k_len = s.shape[-2], s.shape[-1]
        qi = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 0)
        kj = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 1)
        s = jnp.where(kj <= qi, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("...hqk,...hkd->...hqd", p, v.astype(jnp.float32))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return out / l_safe[..., None], m + jnp.log(l_safe)


def _partial_bwd_reference(q, k, v, do, lse, delta, scale, diag):
    """Blockwise gradients given the *global* lse/delta row statistics."""
    num_kv_heads = k.shape[-3]
    group = q.shape[-3] // num_kv_heads
    kr, vr = _repeat_kv(k, group), _repeat_kv(v, group)
    s = jnp.einsum("...hqd,...hkd->...hqk", q.astype(jnp.float32),
                   kr.astype(jnp.float32)) * scale
    if diag:
        q_len, k_len = s.shape[-2], s.shape[-1]
        qi = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 0)
        kj = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 1)
        s = jnp.where(kj <= qi, s, NEG_INF)
    p = jnp.exp(s - lse[..., None])
    do32 = do.astype(jnp.float32)
    dv = jnp.einsum("...hqk,...hqd->...hkd", p, do32)
    dp = jnp.einsum("...hqd,...hkd->...hqk", do32, vr.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("...hqk,...hkd->...hqd", ds, kr.astype(jnp.float32))
    dk = jnp.einsum("...hqk,...hqd->...hkd", ds, q.astype(jnp.float32))
    if group > 1:
        b, h, klen, d = dk.shape
        dk = dk.reshape(b, num_kv_heads, group, klen, d).sum(axis=2)
        dv = dv.reshape(b, num_kv_heads, group, klen, d).sum(axis=2)
    return dq, dk, dv


def _partial_fwd_pallas(q, k, v, scale, diag, block_q, block_k, interpret):
    out, lse_rep = _fwd_pallas(q, k, v, scale=scale, causal=diag,
                               block_q=block_q, block_k=block_k,
                               interpret=interpret)
    return out.astype(jnp.float32), lse_rep[..., 0]


def _partial_bwd_pallas(q, k, v, do, lse, delta, scale, diag, block_q,
                        block_k, interpret):
    lse_rep = jnp.broadcast_to(lse[..., None], (*lse.shape, _LANES))
    return _bwd_pallas(q, k, v, None, lse_rep, do, scale=scale, causal=diag,
                       block_q=block_q, block_k=block_k, interpret=interpret,
                       delta=delta, keep_f32=True)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None, impl: str = "auto",
                   block_q: int = DEFAULT_BLOCK_Q,
                   block_k: int = DEFAULT_BLOCK_K):
    """Exact attention with sequence sharded over ``axis_name``.

    q: [B, H, S_local, D]; k/v: [B, Hk, S_local, D] (local shards).
    """
    out, _ = _ring_fwd(q, k, v, axis_name, causal, scale, impl, block_q,
                       block_k)
    return out


def _resolve(impl):
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "reference"
    if impl not in ("reference", "pallas", "pallas_interpret"):
        raise ValueError(f"unknown attention impl {impl!r}")
    return impl


def _partial_fns(impl, scale, block_q, block_k):
    impl = _resolve(impl)
    if impl == "reference":
        fwd = lambda q, k, v, diag: _partial_fwd_reference(q, k, v, scale,
                                                           diag)
        bwd = lambda q, k, v, do, lse, dl, diag: _partial_bwd_reference(
            q, k, v, do, lse, dl, scale, diag)
        return fwd, bwd
    interp = impl == "pallas_interpret"
    fwd = lambda q, k, v, diag: _partial_fwd_pallas(
        q, k, v, scale, diag, block_q, block_k, interp)
    bwd = lambda q, k, v, do, lse, dl, diag: _partial_bwd_pallas(
        q, k, v, do, lse, dl, scale, diag, block_q, block_k, interp)
    return fwd, bwd


def _ring_fwd(q, k, v, axis_name, causal, scale, impl, block_q, block_k):
    size = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    scale_val = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    fwd_fn, _ = _partial_fns(impl, scale_val, block_q, block_k)
    perm = [(i, (i + 1) % size) for i in range(size)]

    batch, heads, s_local, d = q.shape
    acc0 = jnp.zeros((batch, heads, s_local, d), jnp.float32)
    m0 = jnp.full((batch, heads, s_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((batch, heads, s_local), jnp.float32)

    def step(carry, s):
        k_cur, v_cur, acc, m, l = carry

        def skip(_):
            return jnp.zeros_like(acc), jnp.full_like(m, NEG_INF)

        def diag_blk(_):
            return fwd_fn(q, k_cur, v_cur, True)

        def full_blk(_):
            return fwd_fn(q, k_cur, v_cur, False)

        if causal:
            # Block at step s originated on shard (idx - s) mod size:
            # s == 0 → my own (diagonal causal); s <= idx → strictly
            # earlier shard (full); otherwise later shard (masked out).
            mode = jnp.where(s == 0, 1, jnp.where(s <= idx, 2, 0))
            o_s, lse_s = lax.switch(mode, [skip, diag_blk, full_blk], None)
        else:
            o_s, lse_s = full_blk(None)
        m_new = jnp.maximum(m, lse_s)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(lse_s - m_new)
        acc = acc * alpha[..., None] + o_s * beta[..., None]
        l = l * alpha + beta
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, acc, m_new, l), None

    (k_fin, v_fin, acc, m, l), _ = lax.scan(
        step, (k, v, acc0, m0, l0), jnp.arange(size))
    del k_fin, v_fin  # back home after a full cycle
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe[..., None]).astype(q.dtype)
    lse = m + jnp.log(l_safe)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, scale, impl, block_q, block_k, res, g):
    q, k, v, out, lse = res
    size = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    scale_val = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    _, bwd_fn = _partial_fns(impl, scale_val, block_q, block_k)
    perm = [(i, (i + 1) % size) for i in range(size)]

    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)

    def step(carry, s):
        k_cur, v_cur, dk_cur, dv_cur, dq_acc = carry

        def skip(_):
            return (jnp.zeros_like(dq0), jnp.zeros_like(dk0),
                    jnp.zeros_like(dv0))

        def diag_blk(_):
            return bwd_fn(q, k_cur, v_cur, g, lse, delta, True)

        def full_blk(_):
            return bwd_fn(q, k_cur, v_cur, g, lse, delta, False)

        if causal:
            mode = jnp.where(s == 0, 1, jnp.where(s <= idx, 2, 0))
            dq_s, dk_s, dv_s = lax.switch(mode, [skip, diag_blk, full_blk],
                                          None)
        else:
            dq_s, dk_s, dv_s = full_blk(None)
        dq_acc = dq_acc + dq_s
        dk_cur = dk_cur + dk_s
        dv_cur = dv_cur + dv_s
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = lax.ppermute(dv_cur, axis_name, perm)
        return (k_nxt, v_nxt, dk_nxt, dv_nxt, dq_acc), None

    (k_fin, v_fin, dk, dv, dq), _ = lax.scan(
        step, (k, v, dk0, dv0, dq0), jnp.arange(size))
    del k_fin, v_fin
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_fwd, _ring_bwd)
