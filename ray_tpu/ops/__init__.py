"""ray_tpu.ops — Pallas TPU kernels and their reference implementations.

The hot ops of the compute path. Each op ships (a) a pure-jnp reference
implementation (used on CPU and as the ground truth in tests) and (b) a
Pallas TPU kernel tuned for MXU/VMEM, selected automatically on TPU
backends.
"""

from .attention import dot_product_attention, flash_attention  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
