"""RL benchmark: PPO learner samples/sec/chip (BASELINE.json north-star
metric name) + IMPALA end-to-end sampling throughput.

Prints one JSON line per metric. The reference publishes no number for
this metric (BASELINE.json ``published: {}``), so ``vs_baseline`` is
null — the value itself is the record the next round compares against.
Run: ``python bench_rl.py [--quick]``.
"""

from __future__ import annotations

import json
import sys
import time

import jax

import ray_tpu
from ray_tpu.rl import CartPoleEnv, ImpalaConfig, PPOConfig

QUICK = "--quick" in sys.argv


def bench_ppo_learner() -> None:
    """Learner-side SGD throughput: env steps consumed per second per
    chip (reference metric: RLlib learner ``num_env_steps_trained``
    throughput)."""
    algo = (PPOConfig()
            .environment(CartPoleEnv)
            .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                      rollout_fragment_length=256)
            .training(num_sgd_iter=8, sgd_minibatch_size=512)
            .build())
    iters = 2 if QUICK else 5
    algo.train()                               # warm compile + workers
    t0 = time.perf_counter()
    steps_trained = 0
    for _ in range(iters):
        result = algo.train()
        # each sampled step is consumed num_sgd_iter times by the learner
        steps_trained += (result["num_env_steps_sampled"]
                          * algo.config.num_sgd_iter)
    dt = time.perf_counter() - t0
    algo.stop()
    n_dev = len(jax.devices())
    print(json.dumps({
        "metric": "ppo_learner_samples_per_sec_per_chip",
        "value": round(steps_trained / dt / n_dev, 1),
        "unit": "samples/s/chip",
        "vs_baseline": None,
        "detail": {"n_devices": n_dev,
                   "backend": jax.default_backend(),
                   "env_steps_sampled_per_sec":
                       round(steps_trained / algo.config.num_sgd_iter / dt,
                             1)},
    }), flush=True)


def bench_impala_throughput() -> None:
    algo = (ImpalaConfig()
            .environment(CartPoleEnv)
            .rollouts(num_rollout_workers=4, num_envs_per_worker=4,
                      rollout_fragment_length=128)
            .training(num_sgd_iter=1)
            .build())
    iters = 4 if QUICK else 12
    algo.train()
    t0 = time.perf_counter()
    sampled = 0
    for _ in range(iters):
        sampled += algo.train()["num_env_steps_sampled"]
    dt = time.perf_counter() - t0
    algo.stop()
    print(json.dumps({
        "metric": "impala_env_steps_per_sec",
        "value": round(sampled / dt, 1),
        "unit": "steps/s",
        "vs_baseline": None,
        "detail": {"num_rollout_workers": 4, "num_envs_per_worker": 4},
    }), flush=True)


def main():
    ray_tpu.init(num_cpus=8)
    bench_ppo_learner()
    bench_impala_throughput()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
