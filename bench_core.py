"""Core control-plane microbenchmarks (ray_perf port).

Measures the runtime primitives with the SAME metric names the
reference's harness publishes (``python/ray/_private/ray_perf.py:93-260``
→ ``release/release_logs/2.7.0/microbenchmark.json``), so every row of
BASELINE.md's single-node table is directly comparable.

Prints one JSON line per metric:
    {"metric", "value", "unit", "vs_baseline"}
where vs_baseline = ours / reference (higher is better), then a summary
line with the geometric mean. Run: ``python bench_core.py [--quick]``.
"""

from __future__ import annotations

import json
import sys
import threading
import time

import numpy as np

import ray_tpu

# BASELINE.md single-node numbers (reference release 2.7.0 microbenchmark)
BASELINES = {
    "single_client_tasks_sync": 1312.0,
    "single_client_tasks_async": 10739.0,
    "1_1_actor_calls_sync": 2256.0,
    "1_1_actor_calls_async": 7615.0,
    "1_1_actor_calls_concurrent": 4746.0,
    "1_n_actor_calls_async": 10134.0,
    "n_n_actor_calls_async": 30848.0,
    "single_client_put_gigabytes": 18.0,
    "single_client_get_object_containing_10k_refs": 14.8,
    "single_client_wait_1k_refs": 5.5,
}

QUICK = "--quick" in sys.argv
DURATION = 1.0 if QUICK else 3.0


def timeit(name: str, fn, multiplier: int = 1, unit: str = "ops/s"):
    fn()                                   # warmup
    count = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < DURATION:
        fn()
        count += 1
    dt = time.perf_counter() - t0
    rate = count * multiplier / dt
    base = BASELINES.get(name)
    rec = {"metric": name, "value": round(rate, 2), "unit": unit,
           "vs_baseline": round(rate / base, 3) if base else None}
    print(json.dumps(rec), flush=True)
    return rec


@ray_tpu.remote
def tiny():
    return b"ok"


@ray_tpu.remote
class Tiny:
    def m(self):
        return b"ok"


def main():
    # store sized so the put benchmark never crosses the spill threshold
    ray_tpu.init(num_cpus=8, object_store_memory=4 << 30)
    results = []

    results.append(timeit(
        "single_client_tasks_sync",
        lambda: ray_tpu.get(tiny.remote())))

    results.append(timeit(
        "single_client_tasks_async",
        lambda: ray_tpu.get([tiny.remote() for _ in range(100)]),
        multiplier=100))

    a = Tiny.remote()
    ray_tpu.get(a.m.remote())
    results.append(timeit(
        "1_1_actor_calls_sync",
        lambda: ray_tpu.get(a.m.remote())))

    results.append(timeit(
        "1_1_actor_calls_async",
        lambda: ray_tpu.get([a.m.remote() for _ in range(100)]),
        multiplier=100))

    c = Tiny.options(max_concurrency=16).remote()
    ray_tpu.get(c.m.remote())
    results.append(timeit(
        "1_1_actor_calls_concurrent",
        lambda: ray_tpu.get([c.m.remote() for _ in range(100)]),
        multiplier=100))

    # zero-CPU actors: the pool must not exhaust the node's CPU slots
    # (reference microbenchmark actors are scheduling-weightless too)
    pool = [Tiny.options(num_cpus=0).remote() for _ in range(8)]
    ray_tpu.get([x.m.remote() for x in pool], timeout=60)
    results.append(timeit(
        "1_n_actor_calls_async",
        lambda: ray_tpu.get([x.m.remote() for x in pool
                             for _ in range(12)]),
        multiplier=12 * len(pool)))

    # n submitting threads, n actors (reference: n drivers)
    def n_n_round():
        def drive(actor):
            ray_tpu.get([actor.m.remote() for _ in range(25)])
        threads = [threading.Thread(target=drive, args=(x,)) for x in pool]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    results.append(timeit("n_n_actor_calls_async", n_n_round,
                          multiplier=25 * len(pool)))

    data = np.zeros(128 << 20, dtype=np.uint8)   # 128 MiB

    def put_round():
        refs = [ray_tpu.put(data) for _ in range(4)]
        ray_tpu.free(refs)      # immediate free: keep the store unspilled

    put_round()                                  # warmup
    count = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < DURATION:
        put_round()
        count += 1
    dt = time.perf_counter() - t0
    gib = count * 4 * 128 / 1024 / dt
    # this row is memcpy-bound: a put is exactly one copy into shm, so
    # the machine's single-thread copy bandwidth caps it — measure that
    # ceiling here so the artifact shows efficiency vs THIS box, not
    # just vs the reference's (multi-GB/s-memcpy) release hardware
    src = np.ones(128 << 20, np.uint8)
    dst = np.empty(128 << 20, np.uint8)
    dst[:] = 0                                  # fault pages in
    t0 = time.perf_counter()
    for _ in range(3):
        np.copyto(dst, src)
    ceiling = 3 * 128 / 1024 / (time.perf_counter() - t0)
    rec = {"metric": "single_client_put_gigabytes",
           "value": round(gib, 3), "unit": "GiB/s",
           "vs_baseline": round(
               gib / BASELINES["single_client_put_gigabytes"], 3),
           "detail": {"hw_one_copy_ceiling_gibs": round(ceiling, 2),
                      "vs_hw_ceiling": round(gib / ceiling, 3)}}
    print(json.dumps(rec), flush=True)
    results.append(rec)

    refs_10k = [ray_tpu.put(i) for i in range(10_000)]
    box = ray_tpu.put(refs_10k)
    results.append(timeit(
        "single_client_get_object_containing_10k_refs",
        lambda: ray_tpu.get(box)))

    refs_1k = [ray_tpu.put(i) for i in range(1_000)]
    results.append(timeit(
        "single_client_wait_1k_refs",
        lambda: ray_tpu.wait(refs_1k, num_returns=1000, timeout=30)))

    scored = [x for x in results if x.get("vs_baseline")]
    geo = float(np.exp(np.mean([np.log(x["vs_baseline"]) for x in scored])))
    print(json.dumps({
        "metric": "core_microbenchmark_geomean_vs_reference",
        "value": round(geo, 3), "unit": "x",
        "vs_baseline": round(geo, 3),
        "detail": {x["metric"]: x["vs_baseline"] for x in scored},
    }), flush=True)
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
