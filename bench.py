"""Headline benchmark: GPT pretraining throughput on the local chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: training tokens/sec/chip on a ~350M-param GPT (gpt2-medium shape,
bf16 activations, remat, fused single-program train step). The
reference's north-star target (BASELINE.json) is >=35% MFU for GPT
pretraining on TPU; `vs_baseline` is achieved-MFU / 0.35, so 1.0 means
the north-star bar, higher is better.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp

# bf16 peak FLOPs per chip by generation.
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "cpu": 5e11,  # nominal, so CPU smoke runs still produce a number
}

MFU_TARGET = 0.35  # BASELINE.json north star: >=35% MFU


def _chip_gen() -> str:
    if jax.default_backend() in ("cpu",):
        return "cpu"
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    return gen if gen in PEAK_FLOPS else "v5e"


def _acquire_backend_or_die(total_budget_s: float,
                            attempt_timeout_s: float) -> None:
    """Initialize the JAX backend: fail fast per attempt, retry with
    backoff within a total budget.

    A wedged TPU plugin *hangs* in an acquire-retry sleep inside
    `jax.devices()` instead of raising (BENCH_r04: rc=1 UNAVAILABLE,
    MULTICHIP_r04/r05: chip unacquirable for the full 240s). The old
    one-shot watchdog burned the whole budget on a single hung attempt
    — but the wedge is usually a *stale holder* (a crashed bench still
    owning the chip), which clears between attempts. So: probe in a
    SUBPROCESS with a short per-attempt timeout (a hung attempt is
    killed, releasing its half-acquired state — an in-process thread
    can't be), back off, and retry until the budget runs out; only
    then emit the JSON error artifact. A successful probe proves the
    chip is acquirable NOW, and the main process initializes under a
    short watchdog.
    """
    import subprocess
    import sys as _sys
    import threading

    deadline = time.monotonic() + total_budget_s
    backoff = 5.0
    attempt = 0
    last_err = None
    acquired = False
    while time.monotonic() < deadline - 1.0:
        attempt += 1
        per_try = min(attempt_timeout_s, deadline - time.monotonic())
        try:
            proc = subprocess.run(
                [_sys.executable, "-c",
                 "import jax; print(len(jax.devices()))"],
                capture_output=True, text=True, timeout=per_try)
        except subprocess.TimeoutExpired:
            last_err = (f"attempt {attempt}: backend init exceeded "
                        f"{per_try:.0f}s (chip unacquirable; "
                        "acquire-retry wedge)")
        else:
            if proc.returncode == 0:
                acquired = True
                break       # chip acquirable now: init for real below
            tail = (proc.stderr or proc.stdout or "").strip(
                ).splitlines()[-1:] or ["<no output>"]
            last_err = (f"attempt {attempt}: backend init failed: "
                        f"{tail[0]}")
        print(f"[bench] {last_err}; retrying in {backoff:.0f}s",
              file=_sys.stderr, flush=True)
        if time.monotonic() + backoff >= deadline:
            break
        time.sleep(backoff)
        backoff = min(backoff * 2, 60.0)
    if not acquired:
        print(json.dumps({
            "metric": "gpt_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "error": (f"TPU backend unacquirable after {attempt} "
                      f"attempts within {total_budget_s:.0f}s; last: "
                      + (last_err or "<no attempt completed>")),
        }), flush=True)
        os._exit(1)

    # main-process init under a watchdog: the subprocess probe said the
    # chip is free, so a hang here means we lost a race — budget the
    # remaining time rather than wedging the driver
    done = {}

    def probe():
        try:
            done["devices"] = len(jax.devices())
        except Exception as exc:  # backend raised (e.g. UNAVAILABLE)
            done["error"] = f"{type(exc).__name__}: {exc}"

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(max(attempt_timeout_s, deadline - time.monotonic()))
    err = None
    if t.is_alive():
        err = ("TPU backend init hung in the main process after a "
               "successful subprocess probe (lost an acquire race)")
    elif "error" in done:
        err = f"TPU backend init failed: {done['error']}"
    if err is not None:
        print(json.dumps({
            "metric": "gpt_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "error": err,
        }), flush=True)
        os._exit(1)


def main():
    _acquire_backend_or_die(
        float(os.environ.get("RTPU_BENCH_ACQUIRE_TIMEOUT", "240")),
        float(os.environ.get("RTPU_BENCH_ACQUIRE_ATTEMPT_TIMEOUT", "45")))
    from ray_tpu.models import (GPT, gpt2_medium, init_train_state,
                                make_optimizer, make_train_step)
    from ray_tpu.models.training import batch_shardings, flops_per_token
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    on_cpu = jax.default_backend() == "cpu"
    n_dev = len(jax.devices())
    # batch scales with device count so act_batch stays shardable over dp.
    if on_cpu:
        from ray_tpu.models import llama_tiny
        cfg = llama_tiny()
        batch, seq, steps, warmup = 2 * n_dev, 128, 4, 2
    else:
        # "dots" remat saves matmul outputs (recompute only elementwise):
        # ~38.4% -> ~41% MFU on v5e; b12/chip is the largest batch that
        # fits HBM with the saved activations (b16 OOMs by 1.7G)
        cfg = gpt2_medium(max_seq_len=1024, remat_policy="dots")
        batch, seq, steps, warmup = 12 * n_dev, 1024, 20, 3

    mesh = None
    model_kwargs = {}
    if n_dev > 1:
        mesh = build_mesh(MeshSpec(dp=-1).resolve(n_dev))
        model_kwargs["mesh"] = mesh
    model = GPT(cfg, **model_kwargs)
    opt = make_optimizer(total_steps=steps + warmup)
    state = init_train_state(model, opt, jax.random.PRNGKey(0), mesh=mesh)
    step = make_train_step(model, opt, mesh=mesh)

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    batch_dict = {"tokens": tokens}
    if mesh is not None:
        batch_dict = {"tokens": jax.device_put(tokens,
                                               batch_shardings(mesh))}

    # NB: sync via host transfer (float()) — block_until_ready returns
    # early on the experimental axon PJRT backend.
    for _ in range(warmup):
        state, metrics = step(state, batch_dict)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_dict)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tokens_per_sec = steps * tokens_per_step / dt
    tokens_per_sec_chip = tokens_per_sec / n_dev
    flops_tok = flops_per_token(cfg)
    mfu = tokens_per_sec_chip * flops_tok / PEAK_FLOPS[_chip_gen()]

    print(json.dumps({
        "metric": "gpt_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / MFU_TARGET, 4),
        "detail": {
            "model": "gpt2_medium" if not on_cpu else "llama_tiny",
            "n_params": cfg.n_params,
            "batch": batch, "seq": seq, "steps": steps,
            "n_devices": n_dev,
            "backend": jax.default_backend(),
            "chip": _chip_gen(),
            "mfu": round(mfu, 4),
            "step_time_s": round(dt / steps, 4),
        },
    }))


if __name__ == "__main__":
    main()
